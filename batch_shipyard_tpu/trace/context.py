"""Trace-context identity and propagation.

A trace context is three ids::

    trace_id        one per job submission (born at action_jobs_add)
    span_id         the current operation's own id
    parent_span_id  the operation that caused it (None at the root)

Propagation path (CLI -> fleet -> state/queue -> agent -> task):

  * ``jobs add`` creates one context per job; the SUBMIT span is
    recorded store-side and every task entity is stamped with
    ``trace_id`` + a per-task root ``trace_span_id`` (child of the
    submit span). Queue messages carry ``trace_id`` so a redelivered
    message stays attributable even if the entity read races a retry.
  * The node agent attaches the task row's ids to every goodput event
    and trace span it emits (claim/backoff/requeue/rendezvous/run),
    and exports the context into the task process env
    ($SHIPYARD_TRACE_ID / $SHIPYARD_TRACE_SPAN_ID, plus the
    $SHIPYARD_TRACE_FILE JSONL sink — docker path remap in
    task_runner, the goodput-file pattern).
  * Inside the task, spans.record()/phase() read the env lazily: the
    task's exported span id becomes the parent of every program span,
    and goodput/events.record() attaches the same ids so the goodput
    intervals of a run join its trace for export.

Ids are short hex (uuid4-derived): 16 chars for trace ids, 8 for span
ids — long enough for fleet-lifetime uniqueness, short enough to read
in a terminal.
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Optional

# Env contract exported into every task process by the node agent.
TRACE_ID_ENV = "SHIPYARD_TRACE_ID"
TRACE_SPAN_ENV = "SHIPYARD_TRACE_SPAN_ID"
# Process-local span sink (JSONL), agent-ingested post-task — the
# $SHIPYARD_GOODPUT_FILE pattern.
TRACE_FILE_ENV = "SHIPYARD_TRACE_FILE"

# Task/job entity columns (written at submit, read by the agent and
# `jobs tasks list`). A task row stores its ROOT span (child of the
# job's submit span) plus that parent, so the agent can emit the
# task-run span under the right id without re-reading the job entity.
COL_TRACE_ID = "trace_id"
COL_TRACE_SPAN = "trace_span_id"
COL_TRACE_PARENT = "trace_parent_span_id"


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """An immutable (trace, span, parent) triple."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None

    @classmethod
    def new(cls) -> "TraceContext":
        """A fresh root context (the submit span of a new trace)."""
        return cls(trace_id=new_trace_id(), span_id=new_span_id())

    def child(self) -> "TraceContext":
        """A new span caused by this one."""
        return TraceContext(trace_id=self.trace_id,
                            span_id=new_span_id(),
                            parent_span_id=self.span_id)

    @classmethod
    def from_entity(cls, entity: dict) -> Optional["TraceContext"]:
        """Context stored on a task/job entity, or None for legacy
        rows submitted before tracing existed. A row with a trace id
        but NO span id (partial merge, foreign writer) is also None:
        minting a fresh id per call would hand every caller a
        different 'root' and silently shred the parent chain —
        untraced degrades cleanly, a broken chain does not."""
        trace_id = entity.get(COL_TRACE_ID)
        span_id = entity.get(COL_TRACE_SPAN)
        if not trace_id or not span_id:
            return None
        parent = entity.get(COL_TRACE_PARENT)
        return cls(trace_id=str(trace_id), span_id=str(span_id),
                   parent_span_id=str(parent) if parent else None)

    def entity_columns(self) -> dict[str, str]:
        """The columns a task/job row stores for this context."""
        out = {COL_TRACE_ID: self.trace_id,
               COL_TRACE_SPAN: self.span_id}
        if self.parent_span_id:
            out[COL_TRACE_PARENT] = self.parent_span_id
        return out

    @classmethod
    def from_env(cls) -> Optional["TraceContext"]:
        """The context the agent exported into THIS process, or None
        outside pool tasks (tracing is then a no-op). Both vars must
        be present — same degrade-to-None rule as from_entity."""
        trace_id = os.environ.get(TRACE_ID_ENV)
        span_id = os.environ.get(TRACE_SPAN_ENV)
        if not trace_id or not span_id:
            return None
        return cls(trace_id=trace_id, span_id=span_id)

    def env(self) -> dict[str, str]:
        """The env block the agent exports into a task process."""
        return {TRACE_ID_ENV: self.trace_id,
                TRACE_SPAN_ENV: self.span_id}

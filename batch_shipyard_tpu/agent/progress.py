"""Task progress beats: the liveness contract behind the wedge watchdog.

The TPU failure mode that motivates this (TPU_WEDGE_REPORT.md) is a
process that stays ALIVE but makes no progress forever — `jax.devices()`
blocked in the runtime, a collective stuck on a dead ICI peer. Wall-time
limits catch runaways, heartbeats catch dead nodes; neither catches a
wedged-but-breathing task. Progress beats do: the agent exports
$SHIPYARD_PROGRESS_FILE into every task env, instrumented workloads
touch it on every unit of progress (the train-step wrappers in
parallel/train.py beat on every step call), and the task runner's
watchdog kills any task whose spec declares `progress_deadline_seconds`
once the file goes stale past that deadline — converting an unbounded
hang into a bounded retry through the retry supervisor.

Beats are throttled (at most one mtime write per BEAT_INTERVAL) so a
microsecond step loop never turns the liveness file into an I/O hot
path. With no sink configured the recorder is a no-op: workloads run
unchanged outside pools, exactly like the goodput recorder.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

# Env var the agent exports into every task: the liveness file the
# watchdog stats. Process spawn counts as the first beat (the runner
# seeds the file), so un-instrumented tasks only ever trip the
# watchdog if they opt in via progress_deadline_seconds AND stall.
PROGRESS_FILE_ENV = "SHIPYARD_PROGRESS_FILE"

# The task's own watchdog deadline, exported alongside the file so the
# throttle can scale itself: a fixed 1s throttle against a ~1s deadline
# would drop the very beats that prove liveness, and the watchdog would
# kill a task that is progressing every step.
PROGRESS_DEADLINE_ENV = "SHIPYARD_PROGRESS_DEADLINE"

# Throttle ceiling: minimum seconds between mtime writes from beat()
# when no (or a generous) deadline is exported.
BEAT_INTERVAL = 1.0

_last_beat_at = 0.0


def _throttle_seconds() -> float:
    """Beats must land well inside the watchdog deadline: throttle at
    a quarter of the exported deadline, capped at BEAT_INTERVAL."""
    raw = os.environ.get(PROGRESS_DEADLINE_ENV)
    if raw:
        try:
            return min(BEAT_INTERVAL, max(0.01, float(raw) / 4.0))
        except ValueError:
            pass
    return BEAT_INTERVAL


def progress_path() -> Optional[str]:
    """The liveness file for THIS process, or None (beats disabled)."""
    return os.environ.get(PROGRESS_FILE_ENV) or None


def beat() -> None:
    """Record one unit of progress: bump the liveness file's mtime —
    the only signal the watchdog reads. No-op when unset; never
    raises — a liveness write must not fail the work it measures."""
    global _last_beat_at
    path = progress_path()
    if path is None:
        return
    now = time.monotonic()
    if now - _last_beat_at < _throttle_seconds():
        return
    _last_beat_at = now
    try:
        os.utime(path, None)
    except OSError:
        # First beat before the runner's seed (or the file was
        # removed underneath us): create it.
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w", encoding="utf-8"):
                pass
        except OSError:
            pass


def seed(path: str) -> None:
    """Write the initial beat (process spawn) so the watchdog's clock
    starts at launch, not at epoch 0."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8"):
            pass
    except OSError:
        pass


def last_beat(path: str) -> Optional[float]:
    """Wall-clock time of the task's most recent beat (file mtime), or
    None when the file does not exist."""
    try:
        return os.stat(path).st_mtime
    except OSError:
        return None


# --------------------------- sched hints ---------------------------
#
# The liveness beat proves a task is MOVING; scheduling hints say what
# it would COST to stop it. Instrumented workloads publish
# {step, ckpt_step, step_seconds, cache_identity} to the hints file
# the agent exports ($SHIPYARD_SCHED_HINTS_FILE); the agent mirrors it
# into the task row's sched_hints column on each heartbeat, where the
# preemption sweep's shared victim-cost policy
# (sched/policy.py victim_cost_from_row) prices replay rework from it.
# Purely advisory, same contract as beats: no sink → no-op, a failed
# write never fails the work it describes.

SCHED_HINTS_FILE_ENV = "SHIPYARD_SCHED_HINTS_FILE"


def sched_hints_path() -> Optional[str]:
    """The hints file for THIS process, or None (hints disabled)."""
    return os.environ.get(SCHED_HINTS_FILE_ENV) or None


def record_sched_hints(step: Optional[int] = None,
                       ckpt_step: Optional[int] = None,
                       step_seconds: Optional[float] = None,
                       cache_identity: Optional[str] = None) -> None:
    """Publish this task's preemption-cost inputs (atomic
    tmp+rename, so the agent's heartbeat read never sees a torn
    write). Fields left None are omitted — callers report what they
    know (a checkpointer knows ckpt_step, a step loop knows
    step/step_seconds)."""
    path = sched_hints_path()
    if path is None:
        return
    hints: dict = {}
    if step is not None:
        hints["step"] = int(step)
    if ckpt_step is not None:
        hints["ckpt_step"] = int(ckpt_step)
    if step_seconds is not None:
        hints["step_seconds"] = float(step_seconds)
    if cache_identity:
        hints["cache_identity"] = str(cache_identity)
    if not hints:
        return
    try:
        prior = read_sched_hints(path) or {}
        prior.update(hints)
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(prior, fh, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass


def read_sched_hints(path: str) -> Optional[dict]:
    """The hints dict at ``path``, or None (absent/corrupt — a torn
    or junk file is advisory data lost, never an agent crash)."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None

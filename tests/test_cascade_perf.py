"""Cascade lease-gated replication + perf pipeline tests (reference:
cascade/cascade.py lease gate :574-635, perf.py, graph.py)."""

import concurrent.futures
import threading
import time

from batch_shipyard_tpu.agent import perf
from batch_shipyard_tpu.agent.cascade import (
    CascadeImageProvisioner, global_resources_loaded,
    populate_global_resources)
from batch_shipyard_tpu.agent.node_agent import NodeIdentity
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.graph import perf_graph
from batch_shipyard_tpu.state.memory import MemoryStateStore


class FakeAgent:
    """Just enough agent surface for the provisioner."""

    def __init__(self, store, pool_id, node_id):
        self.store = store
        self.identity = NodeIdentity(
            pool_id=pool_id, node_id=node_id, node_index=0,
            hostname=node_id, internal_ip="10.0.0.1")
        self.stop_event = threading.Event()


def test_populate_and_loaded_flag():
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["img1:latest", "img2:v2"],
                              concurrent_downloads=2)
    agent = FakeAgent(store, "p", "n0")
    assert not global_resources_loaded(store, "p", "n0")
    prov = CascadeImageProvisioner(store, puller=lambda kind, img: 0)
    prov.distribute_global_resources(agent)
    assert global_resources_loaded(store, "p", "n0")


def test_concurrency_gate_bounds_parallel_pulls():
    """With K lock slots, at most K nodes pull the same image at
    once (the reference's hash.{0..N} blob-lease gate)."""
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["big:latest"],
                              concurrent_downloads=2)
    active = []
    max_active = []
    lock = threading.Lock()

    def slow_pull(kind, image):
        with lock:
            active.append(1)
            max_active.append(len(active))
        time.sleep(0.1)
        with lock:
            active.pop()
        return 0

    def node_run(idx):
        agent = FakeAgent(store, "p", f"n{idx}")
        prov = CascadeImageProvisioner(store, puller=slow_pull)
        prov.distribute_global_resources(agent)

    with concurrent.futures.ThreadPoolExecutor(6) as pool:
        list(pool.map(node_run, range(6)))
    assert max(max_active) <= 2
    # every node finished its pull
    for idx in range(6):
        assert global_resources_loaded(store, "p", f"n{idx}")


def test_failed_pull_not_recorded_loaded():
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["bad:latest"])
    agent = FakeAgent(store, "p", "n0")
    prov = CascadeImageProvisioner(store, puller=lambda k, i: 1)
    prov.distribute_global_resources(agent)
    assert not global_resources_loaded(store, "p", "n0")


def test_kind_qualified_keys_shared_between_paths():
    """__call__ with kind must hit the same manifest rows as
    populate_global_resources."""
    store = MemoryStateStore()
    populate_global_resources(store, "p", [],
                              singularity_images=["simg:1"])
    pulls = []
    prov = CascadeImageProvisioner(
        store, puller=lambda kind, img: pulls.append((kind, img)) or 0)
    agent = FakeAgent(store, "p", "n0")
    prov(agent, ["simg:1"], kind="singularity")
    assert pulls == [("singularity", "simg:1")]


def test_perf_pipeline_and_gantt():
    store = MemoryStateStore()
    t0 = time.time()
    perf.emit(store, "p", "n0", "nodeprep", "start", timestamp=t0)
    perf.emit(store, "p", "n0", "cascade", "pull.start:img",
              timestamp=t0 + 0.5)
    perf.emit(store, "p", "n0", "cascade", "pull.end:img",
              timestamp=t0 + 2.0)
    perf.emit(store, "p", "n0", "cascade", "global_resources_loaded",
              timestamp=t0 + 2.1)
    perf.emit(store, "p", "n0", "nodeprep", "end", timestamp=t0 + 2.5)
    data = perf_graph.coalesce_data(store, "p")
    assert abs(data["nodes"]["n0"]["nodeprep"]["seconds"] - 2.5) < 1e-6
    assert abs(data["images"]["n0"]["img"] - 1.5) < 1e-6
    assert abs(data["nodes"]["n0"]["global_resources_loaded"][
        "seconds"] - 2.1) < 1e-6
    text = perf_graph.render_text_gantt(data)
    assert "nodeprep" in text and "#" in text


def test_perf_event_collision_bump():
    store = MemoryStateStore()
    ts = time.time()
    for _ in range(5):
        perf.emit(store, "p", "n0", "s", "same_event", timestamp=ts)
    assert len(perf.query(store, "p")) == 5

"""Static consistency — now a thin wrapper over `shipyard lint`.

The table/event/span/state/CLI-action AST scans that used to live
here are registered analyzer rules (batch_shipyard_tpu/analysis/,
PR 11); each historical test keeps its name and coverage but runs
the corresponding rule over the real tree, so tier-1 sees the same
gates while the CLI (`shipyard lint`) and tests/test_analysis.py
share one implementation. Checks with no analyzer analog (committed
bench artifacts, tools/ cross-file wiring) stay native below.
"""

import ast
import pathlib

from batch_shipyard_tpu import analysis
from batch_shipyard_tpu.state import names

PACKAGE = pathlib.Path(names.__file__).resolve().parent.parent

_CTX = analysis.AnalysisContext.from_tree()


def _run(rule_id: str) -> list:
    """Active findings of one analyzer rule over the real tree
    (inline-suppressed sites excluded, like the lint gate)."""
    active, _ = analysis.run_rules(_CTX, [rule_id])
    return active


def _fail_lines(findings) -> str:
    return "\n".join(f.render() for f in findings)


def test_declared_table_values_are_unique():
    declared = {a for a in dir(names) if a.startswith("TABLE_")}
    values = {getattr(names, a) for a in declared}
    assert len(values) == len(declared), (
        "two TABLE_* constants in state/names.py share a value")


def test_every_table_literal_is_declared():
    findings = _run("registry-table-undeclared")
    assert not findings, _fail_lines(findings)


def test_goodput_table_declared():
    # The event log's table rides the same registry as every other
    # coordination surface.
    assert names.TABLE_GOODPUT == "goodput"
    assert hasattr(names, "TABLE_GOODPUT")
    # PR 11: the schedule table joined the registry when the analyzer
    # caught its hand-rolled literal.
    assert names.TABLE_JOBSCHEDULES == "jobschedules"


def test_goodput_program_constants_are_declared():
    """Every event-kind constant referenced through a goodput/events
    alias resolves there and is registered in EVENT_KINDS (analyzer
    rule goodput-kind-undeclared, generalizing the old PROGRAM_*
    scan)."""
    findings = _run("goodput-kind-undeclared")
    assert not findings, _fail_lines(findings)


def test_task_state_literals_come_from_the_registry():
    findings = _run("registry-state-literal")
    assert not findings, _fail_lines(findings)


def test_quarantine_and_health_names_declared():
    """PR 5's new vocabulary rides the registry: the quarantined task
    state is terminal (and a TASK_STATE), and the node health columns
    are single-sourced."""
    assert names.TASK_STATE_QUARANTINED == "quarantined"
    assert names.TASK_STATE_QUARANTINED in names.TASK_STATES
    assert names.TASK_STATE_QUARANTINED in names.TERMINAL_TASK_STATES
    assert set(names.TERMINAL_TASK_STATES) <= set(names.TASK_STATES)
    assert names.NODE_COL_HEALTH == "health"
    assert names.NODE_COL_QUARANTINED == "quarantined"


def test_task_and_backoff_event_constants_are_declared():
    """The retry supervisor's TASK_RETRY/TASK_BACKOFF (and every
    other event constant) are covered by the undeclared-kind rule;
    the backoff pricing invariant is covered by the unpriced-kind
    rule plus the direct asserts."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    findings = _run("goodput-kind-undeclared")
    findings += _run("goodput-kind-unpriced")
    assert not findings, _fail_lines(findings)
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_BACKOFF] == "backoff"
    assert "backoff" in accounting.BADPUT_CATEGORIES
    # Server-side task-factory expansion is priced as its own
    # scheduling-badput category (the 10^6 bench's submit leg).
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_EXPANSION] == "expansion"
    assert "expansion" in accounting.BADPUT_CATEGORIES


def test_preemption_and_resize_names_declared():
    """PR 10's vocabulary: preempted is NON-terminal and claimable;
    the TASK_PREEMPT_*/GANG_RESIZE kinds are declared+registered
    (rule), actually referenced at emit sites (native scan — dead
    registry check), and the recovery leg is priced."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.trace import spans as trace_spans
    assert names.TASK_STATE_PREEMPTED == "preempted"
    assert names.TASK_STATE_PREEMPTED in names.TASK_STATES
    assert names.TASK_STATE_PREEMPTED not in \
        names.TERMINAL_TASK_STATES
    assert names.TASK_STATE_PREEMPTED in names.CLAIMABLE_TASK_STATES
    assert set(names.CLAIMABLE_TASK_STATES) <= set(names.TASK_STATES)
    findings = _run("goodput-kind-undeclared")
    assert not findings, _fail_lines(findings)
    # Every kind of the family is actually referenced at an emit
    # site — a declared-but-never-emitted kind is dead registry.
    event_attrs = {"TASK_PREEMPT_NOTICE", "TASK_PREEMPT_EXIT",
                   "TASK_PREEMPT_RECOVERY", "GANG_RESIZE"}
    referenced = set()
    for src in _CTX.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in event_attrs:
                referenced.add(node.attr)
    assert event_attrs <= referenced, event_attrs - referenced
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_PREEMPT_RECOVERY] == "preemption_recovery"
    assert "preemption_recovery" in accounting.BADPUT_CATEGORIES
    assert trace_spans.SPAN_PREEMPT in trace_spans.SPAN_KINDS
    assert trace_spans.SPAN_GANG_RESIZE in trace_spans.SPAN_KINDS


def test_eviction_and_migration_names_declared():
    """PR 12's vocabulary: evicted is NON-terminal and claimable
    like preempted; the TASK_EVICTED / TASK_EVICTION_RECOVERY /
    GANG_MIGRATE kinds are declared+registered (rule), actually
    referenced at emit sites (native scan — dead registry check),
    and the eviction/migration legs are priced as their own badput
    categories. The evict/gang_migrate spans ride SPAN_KINDS."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.trace import spans as trace_spans
    assert names.TASK_STATE_EVICTED == "evicted"
    assert names.TASK_STATE_EVICTED in names.TASK_STATES
    assert names.TASK_STATE_EVICTED not in \
        names.TERMINAL_TASK_STATES
    assert names.TASK_STATE_EVICTED in names.CLAIMABLE_TASK_STATES
    findings = _run("goodput-kind-undeclared")
    findings += _run("goodput-kind-unpriced")
    assert not findings, _fail_lines(findings)
    event_attrs = {"TASK_EVICTED", "TASK_EVICTION_RECOVERY",
                   "GANG_MIGRATE"}
    referenced = set()
    for src in _CTX.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in event_attrs:
                referenced.add(node.attr)
    assert event_attrs <= referenced, event_attrs - referenced
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_EVICTION_RECOVERY] == "eviction"
    assert accounting._KIND_CATEGORY[
        gp_events.GANG_MIGRATE] == "migration"
    assert "eviction" in accounting.BADPUT_CATEGORIES
    assert "migration" in accounting.BADPUT_CATEGORIES
    assert trace_spans.SPAN_EVICT in trace_spans.SPAN_KINDS
    assert trace_spans.SPAN_GANG_MIGRATE in trace_spans.SPAN_KINDS


def test_fleet_elasticity_chaos_kinds_wired():
    """The three PR 12 chaos kinds are registered in
    INJECTION_KINDS (validation + --kinds help, which derives from
    it), excluded from the generic default schedule (a single-pool
    generic drill cannot recover from pool_capacity_loss by
    construction), actually APPLIED by the injector, and actually
    requested by at least one drill — a kind nothing injects is
    dead vocabulary."""
    from batch_shipyard_tpu.chaos.plan import (
        DEFAULT_DRILL_KINDS, INJECTION_KINDS)
    new_kinds = {"victim_ignore_notice", "host_loss_resize",
                 "pool_capacity_loss"}
    assert new_kinds <= set(INJECTION_KINDS)
    assert not new_kinds & set(DEFAULT_DRILL_KINDS)
    assert set(DEFAULT_DRILL_KINDS) <= set(INJECTION_KINDS)
    injectors_src = (PACKAGE / "chaos" / "injectors.py").read_text(
        encoding="utf-8")
    drill_src = (PACKAGE / "chaos" / "drill.py").read_text(
        encoding="utf-8")
    for kind in sorted(new_kinds):
        assert f'"{kind}"' in injectors_src, (
            f"chaos kind {kind} has no injector")
        assert f'"{kind}"' in drill_src, (
            f"chaos kind {kind} is not injected by any drill")
    # The rendered --kinds help really names them (derived from
    # INJECTION_KINDS; the wiring rule keeps it derived).
    import click

    from batch_shipyard_tpu.cli import main as cli_main
    ctx = click.Context(cli_main.chaos_plan, info_name="plan")
    rendered = "".join(cli_main.chaos_plan.get_help(ctx).split())
    for kind in sorted(new_kinds):
        assert kind in rendered


def test_fleet_elasticity_dispatched_and_rendered():
    """The fleet-elasticity drills are wired end to end: bench.py
    dispatches the fleet_elasticity workload, benchgen renders the
    committed BENCH_fleet_elasticity.json artifact, and the artifact
    records all three drills passing."""
    import json
    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"fleet_elasticity" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_fleet_elasticity.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_fleet_elasticity.json"
    assert artifact.exists(), (
        "BENCH_fleet_elasticity.json not committed — run "
        "`python bench.py --workloads fleet_elasticity`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["fleet_elasticity"]
    assert data["all_passed"] is True
    assert set(data["drills"]) == {"eviction", "host_resize",
                                   "migration"}
    for entry in data["drills"].values():
        assert entry["passed"] is True
        assert entry["invariants_checked"]
    assert data.get("cpu_marker") is True


def test_control_plane_vocabulary_declared():
    """ISSUE 13's vocabulary: the STORE_OUTAGE / TASK_ADOPTION kinds
    are declared+registered (rule), priced as their own badput
    categories, actually referenced at emit sites (native scan —
    dead registry check); SPAN_AGENT_RESTART rides SPAN_KINDS and is
    emitted; the leader-lease roles and key helpers exist."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.state import leases as state_leases
    from batch_shipyard_tpu.trace import spans as trace_spans
    findings = _run("goodput-kind-undeclared")
    findings += _run("goodput-kind-unpriced")
    findings += _run("trace-span-undeclared")
    assert not findings, _fail_lines(findings)
    event_attrs = {"STORE_OUTAGE", "TASK_ADOPTION",
                   "SPAN_AGENT_RESTART"}
    referenced = set()
    for src in _CTX.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in event_attrs:
                referenced.add(node.attr)
    assert event_attrs <= referenced, event_attrs - referenced
    assert accounting._KIND_CATEGORY[
        gp_events.STORE_OUTAGE] == "store_outage"
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_ADOPTION] == "adoption"
    assert "store_outage" in accounting.BADPUT_CATEGORIES
    assert "adoption" in accounting.BADPUT_CATEGORIES
    assert trace_spans.SPAN_AGENT_RESTART in trace_spans.SPAN_KINDS
    # Leader-lease vocabulary: role registry + key helpers + the
    # heartbeat-published WAL backlog column.
    assert state_leases.ROLE_GANG_JANITOR in \
        state_leases.AGENT_LEADER_ROLES
    assert state_leases.ROLE_PREEMPT_SWEEP in \
        state_leases.AGENT_LEADER_ROLES
    assert names.leader_epoch_key("p", "r") == \
        names.leader_lease_key("p", "r") + ".epoch"
    assert names.NODE_COL_JOURNAL_BACKLOG == "journal_backlog"


def test_control_plane_chaos_kinds_wired():
    """The three ISSUE 13 chaos kinds are registered in
    INJECTION_KINDS (validation + --kinds help, which derives from
    it), excluded from the generic default schedule (a sustained
    outage without the resilient wrapper armed is unrecoverable by
    construction), actually APPLIED by the injector, and actually
    requested by at least one drill — a kind nothing injects is
    dead vocabulary. The three drill flags are rendered by the CLI
    help."""
    from batch_shipyard_tpu.chaos.plan import (
        DEFAULT_DRILL_KINDS, INJECTION_KINDS)
    new_kinds = {"store_outage", "leader_partition", "agent_restart"}
    assert new_kinds <= set(INJECTION_KINDS)
    assert not new_kinds & set(DEFAULT_DRILL_KINDS)
    injectors_src = (PACKAGE / "chaos" / "injectors.py").read_text(
        encoding="utf-8")
    drill_src = (PACKAGE / "chaos" / "drill.py").read_text(
        encoding="utf-8")
    for kind in sorted(new_kinds):
        assert f'"{kind}"' in injectors_src, (
            f"chaos kind {kind} has no injector")
        assert f'"{kind}"' in drill_src, (
            f"chaos kind {kind} is not injected by any drill")
    import click

    from batch_shipyard_tpu.cli import main as cli_main
    ctx = click.Context(cli_main.chaos_plan, info_name="plan")
    rendered = "".join(cli_main.chaos_plan.get_help(ctx).split())
    for kind in sorted(new_kinds):
        assert kind in rendered
    ctx = click.Context(cli_main.chaos_drill, info_name="drill")
    rendered = cli_main.chaos_drill.get_help(ctx)
    for flag in ("--outage", "--partition", "--restart"):
        assert flag in rendered, f"drill flag {flag} not wired"


def test_control_plane_dispatched_and_rendered():
    """The control-plane drills are wired end to end: bench.py
    dispatches the control_plane workload, benchgen renders the
    committed BENCH_control_plane.json artifact, and the artifact
    records all three drills passing."""
    import json
    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"control_plane" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_control_plane.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_control_plane.json"
    assert artifact.exists(), (
        "BENCH_control_plane.json not committed — run "
        "`python bench.py --workloads control_plane`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["control_plane"]
    assert data["all_passed"] is True
    assert set(data["drills"]) == {"store_outage",
                                   "leader_partition",
                                   "agent_restart"}
    for entry in data["drills"].values():
        assert entry["passed"] is True
        assert entry["invariants_checked"]
    assert data.get("cpu_marker") is True


def test_chaos_kinds_all_expressible_in_the_simulator():
    """ISSUE 17: every chaos injection kind maps to a simulator
    adapter (sim/scenarios.py KIND_ADAPTERS) or is explicitly listed
    in SIM_EXCLUDED_KINDS — a kind in neither set is a chaos mode
    the fleet simulator silently cannot model. The exclusion set
    holds exactly the serving kinds (replica/router), which target a
    serving fleet rather than a batch pool and are drilled live
    (chaos/serving_drill.py) instead."""
    from batch_shipyard_tpu.chaos.plan import INJECTION_KINDS
    from batch_shipyard_tpu.sim import scenarios as sim_scenarios
    unmapped = set(INJECTION_KINDS) - set(
        sim_scenarios.KIND_ADAPTERS) - set(
        sim_scenarios.SIM_EXCLUDED_KINDS)
    assert not unmapped, (
        f"chaos kinds with no sim adapter and no exclusion entry: "
        f"{sorted(unmapped)}")
    # No dead adapters either: every adapter key is a real kind.
    dead = set(sim_scenarios.KIND_ADAPTERS) - set(INJECTION_KINDS)
    assert not dead, f"sim adapters for unknown kinds: {sorted(dead)}"
    assert not set(sim_scenarios.SIM_EXCLUDED_KINDS) & set(
        sim_scenarios.KIND_ADAPTERS)


def test_policy_knobs_mirrored_in_settings_and_schema():
    """The sched_policy knob surface is single-sourced: every
    PolicyKnobs field (sched/policy.py) appears by NAME in
    SchedPolicySettings (config/settings.py) and in the pool.yaml
    schema's sched_policy block — a knob added in one place but not
    the others would silently fall back to defaults for every pool
    spec."""
    import dataclasses

    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.sched import policy as sched_policy
    knob_fields = {f.name for f in
                   dataclasses.fields(sched_policy.PolicyKnobs)}
    settings_fields = {f.name for f in
                       dataclasses.fields(S.SchedPolicySettings)}
    missing = knob_fields - settings_fields
    assert not missing, (
        f"PolicyKnobs fields absent from SchedPolicySettings: "
        f"{sorted(missing)}")
    schema_src = (PACKAGE / "config" / "schemas" / "pool.yaml"
                  ).read_text(encoding="utf-8")
    for field in sorted(knob_fields):
        assert f"{field}:" in schema_src, (
            f"pool.yaml schema sched_policy block lacks {field}")
    # knobs_from_settings round-trips a fully-populated settings
    # object field-for-field (None falls back to defaults).
    populated = S.SchedPolicySettings(
        claim_scoring=True,
        **{name: 7.0 for name in knob_fields})
    knobs = sched_policy.knobs_from_settings(populated)
    assert all(getattr(knobs, name) == 7.0 for name in knob_fields)
    defaults = sched_policy.knobs_from_settings(None)
    assert defaults == sched_policy.PolicyKnobs()


def test_fleet_sim_dispatched_and_rendered():
    """The fleet-simulator policy proof is wired end to end: bench.py
    dispatches the fleet_sim workload, benchgen renders the committed
    BENCH_fleet_sim.json artifact, and the artifact records >=2,000
    virtual nodes, >=10^5 tasks, every policy bundle on >=3 scenarios
    (including the preemption-wave chaos scenario) with exact
    partitions throughout and per-policy deltas vs baseline."""
    import json

    from batch_shipyard_tpu.sched import policy as sched_policy
    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"fleet_sim" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_fleet_sim.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_fleet_sim.json"
    assert artifact.exists(), (
        "BENCH_fleet_sim.json not committed — run "
        "`python bench.py --workloads fleet_sim`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["fleet_sim"]
    assert data["nodes"] >= 2000
    assert data["tasks"] >= 100_000
    assert data["all_partitions_exact"] is True
    assert data.get("cpu_marker") is True
    assert set(data["policies"]) == set(sched_policy.POLICIES)
    assert len(data["scenarios"]) >= 3
    assert "preemption_wave" in data["scenarios"]
    for scenario, section in data["scenarios"].items():
        assert set(section) == set(sched_policy.POLICIES), scenario
        for policy, row in section.items():
            assert row["partition_exact"] is True, (scenario, policy)
            assert row["fingerprint"]
            if policy != "baseline":
                assert "goodput_ratio_delta" in \
                    row["delta_vs_baseline"], (scenario, policy)


def test_serving_slo_dispatched_and_rendered():
    """The prefix-cache/SLO proof is wired end to end: bench.py
    dispatches the serving_slo workload, benchgen renders the
    committed BENCH_serving_slo.json, and the artifact clears the
    acceptance gates — prefix hit rate > 0.5, prefix-cache-on mean
    AND p99 TTFT strictly below the cache-off control at the same
    seed, and byte-identical greedy outputs between the two arms."""
    import json

    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"serving_slo" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_serving_slo.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_serving_slo.json"
    assert artifact.exists(), (
        "BENCH_serving_slo.json not committed — run "
        "`python bench.py --workloads serving_slo`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["serving_slo"]
    assert data.get("cpu_marker") is True
    assert data["prefix_hit_rate"] > 0.5
    assert data["outputs_identical"] is True
    on, off = data["prefix_cache_on"], data["prefix_cache_off"]
    assert on["completed"] == off["completed"] == \
        data["num_requests"]
    assert on["ttft_mean_ms"] < off["ttft_mean_ms"]
    assert on["ttft_exact_ms"]["p99"] < off["ttft_exact_ms"]["p99"]
    assert on["outputs_sha256"] == off["outputs_sha256"]
    for arm in (on, off):
        assert set(arm["slo_attainment"]) == {
            "interactive", "standard", "batch"}


def test_chaos_kinds_help_lists_node_preempt_notice():
    """The --kinds help derives from INJECTION_KINDS (analyzer rule
    wiring-kinds-help-stale) and the rendered help really names the
    advance-notice kind."""
    from batch_shipyard_tpu.chaos.plan import INJECTION_KINDS
    assert "node_preempt_notice" in INJECTION_KINDS
    findings = _run("wiring-kinds-help-stale")
    assert not findings, _fail_lines(findings)
    import click

    from batch_shipyard_tpu.cli import main as cli_main
    ctx = click.Context(cli_main.chaos_plan, info_name="plan")
    # click wraps long help lines mid-token: collapse whitespace
    # before matching.
    rendered = "".join(cli_main.chaos_plan.get_help(ctx).split())
    assert "node_preempt_notice" in rendered


def test_scheduler_scale_workload_dispatched_and_rendered():
    """The 10^6 proof is wired end to end: bench.py dispatches the
    scheduler_scale workload, benchgen reads the committed
    BENCH_scheduler_scale.json artifact, and the artifact itself
    records a complete, partition-exact 10^6-task run whose submit
    leg (server-side expansion, streaming batched submission) is no
    longer the dominant cost."""
    import json
    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"scheduler_scale" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_scheduler_scale.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_scheduler_scale.json"
    assert artifact.exists(), (
        "BENCH_scheduler_scale.json not committed — run "
        "`python bench.py --workloads scheduler_scale`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["scheduler_scale"]
    assert data["num_tasks"] >= 1_000_000
    assert data["completed"] is True
    assert data["goodput"]["partition_exact"] is True
    assert data["server_side_expansion"] is True
    # Submission must not dominate: the materialization leg is
    # strictly cheaper than the drain, and >= 10x the pre-streaming
    # submitter's 1648 tasks/s.
    assert data["submit_seconds"] < data["run_seconds"]
    assert data["submit_tasks_per_second"] >= 16_480


def test_train_workloads_enable_the_compile_cache():
    findings = _run("wiring-compile-cache-optout")
    assert not findings, _fail_lines(findings)


def _tpu_checks_names():
    """CHECKS keys from tools/tpu_checks.py, by AST (dict literal
    keys plus CHECKS["..."] = ... assignments) — no import of the
    TPU harness."""
    path = PACKAGE.parent / "tools" / "tpu_checks.py"
    tree = ast.parse(path.read_text(encoding="utf-8"),
                     filename=str(path))
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and \
                        target.id == "CHECKS" and \
                        isinstance(node.value, ast.Dict):
                    out |= {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)}
                if isinstance(target, ast.Subscript) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "CHECKS" and \
                        isinstance(target.slice, ast.Constant):
                    out.add(target.slice.value)
    return out


def test_kernel_select_names_are_backed_by_tpu_checks():
    """Every validation name the package consults for impl='auto'
    dispatch (kernel_select.resolve_auto / kernel_validated) must be
    a tools/tpu_checks.py CHECKS entry — a typo'd gate name would
    keep a Pallas fast path off forever with no failing check to say
    why (stays native: tpu_checks.py lives outside the analyzer's
    package scope)."""
    check_names = _tpu_checks_names()
    assert check_names, "could not parse tpu_checks.CHECKS"
    problems = []
    for src in _CTX.python_files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else None)
            if name not in ("resolve_auto", "kernel_validated"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                check = node.args[0].value
                if check not in check_names:
                    problems.append(
                        f"{src.rel}:{node.lineno}: kernel_select "
                        f"gate {check!r} has no tools/tpu_checks.py "
                        f"CHECKS entry")
    assert not problems, "\n".join(problems)


def test_benchgen_phase_and_workload_names_exist():
    """Every silicon-proof phase name tools/benchgen.py binds to
    (p.get("phase") == "X") must be record()-ed by
    tools/silicon_proof.py, and every bench workload a silicon-proof
    phase command invokes (--workloads X) must be dispatched by
    bench.py ("X" in workloads) — a renamed phase cannot silently
    turn a docs section or a pipeline phase into a no-op."""
    tools = PACKAGE.parent / "tools"
    benchgen_tree = ast.parse(
        (tools / "benchgen.py").read_text(encoding="utf-8"))
    proof_src = (tools / "silicon_proof.py").read_text(
        encoding="utf-8")
    proof_tree = ast.parse(proof_src)
    bench_tree = ast.parse(
        (PACKAGE.parent / "bench.py").read_text(encoding="utf-8"))

    recorded = set()
    workloads_invoked = set()
    for node in ast.walk(proof_tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "record" and node.args and \
                isinstance(node.args[0], ast.Constant):
            recorded.add(node.args[0].value)
        # ["...", "--workloads", "X", ...] command lists.
        if isinstance(node, ast.List):
            values = [e.value for e in node.elts
                      if isinstance(e, ast.Constant) and
                      isinstance(e.value, str)]
            for i, value in enumerate(values[:-1]):
                if value == "--workloads":
                    workloads_invoked |= {
                        w.strip() for w in values[i + 1].split(",")}

    referenced = set()
    for node in ast.walk(benchgen_tree):
        # p.get("phase") == "X" comparisons.
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Call) and \
                isinstance(node.left.func, ast.Attribute) and \
                node.left.func.attr == "get" and node.left.args and \
                isinstance(node.left.args[0], ast.Constant) and \
                node.left.args[0].value == "phase":
            for comparator in node.comparators:
                if isinstance(comparator, ast.Constant) and \
                        isinstance(comparator.value, str):
                    referenced.add(comparator.value)
    assert referenced, "no phase references found in benchgen.py"
    missing = referenced - recorded
    assert not missing, (
        f"benchgen.py binds to silicon-proof phases {sorted(missing)} "
        f"that tools/silicon_proof.py never records")

    dispatched = set()
    for node in ast.walk(bench_tree):
        # "X" in workloads dispatch checks.
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], ast.In) and \
                isinstance(node.comparators[0], ast.Name) and \
                node.comparators[0].id == "workloads":
            dispatched.add(node.left.value)
    assert dispatched, "no workload dispatch found in bench.py"
    missing = workloads_invoked - dispatched
    assert not missing, (
        f"silicon_proof.py invokes bench workloads {sorted(missing)} "
        f"that bench.py never dispatches")
    # The kernel phase is wired end to end.
    assert "ring_collectives" in recorded
    assert "ring_collectives" in dispatched


def test_span_kinds_are_declared_in_trace_spans():
    findings = _run("trace-span-undeclared")
    assert not findings, _fail_lines(findings)
    # The span log's table rides the names registry like every other
    # coordination surface.
    assert names.TABLE_TRACE == "trace"


def test_trace_and_profile_fleet_actions_are_wired_in_cli():
    """Widened by the analyzer: EVERY fleet action_* needs a
    cli/main.py call site now, not just the trace/profile family."""
    findings = _run("wiring-cli-action-unwired")
    assert not findings, _fail_lines(findings)


def test_train_loops_never_call_blocking_checkpoint_save():
    findings = _run("jax-blocking-save-in-train")
    assert not findings, _fail_lines(findings)

"""Test harness config: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding paths are testable without TPU hardware (SURVEY.md
section 4: the fake-substrate test strategy the reference lacks)."""

import os
import pathlib
import sys

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Tests must run on a virtual 8-device CPU mesh. The TPU environment's
# sitecustomize imports jax and registers the real TPU backend plugin
# at interpreter startup, so plain env vars are too late — but backend
# *initialization* is lazy, so flipping jax_platforms before the first
# device query still wins.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Belt-and-braces: deregister the accelerator plugin's backend factory.
# A wedged TPU relay can make the plugin's client creation BLOCK (not
# fail) inside xla_bridge.backends() — observed live: runs without the
# jax_platforms config update hung in make_pjrt_c_api_client. With the
# factory gone, nothing in the suite can ever dial the relay.
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import pytest  # noqa: E402

# Installs the pltpu.force_tpu_interpret_mode polyfill on JAX versions
# that lack it (the interpret-mode tests use it as a context manager).
import batch_shipyard_tpu.utils.compat  # noqa: E402,F401


@pytest.fixture()
def tmp_statestore(tmp_path):
    from batch_shipyard_tpu.state.localfs import LocalFSStateStore
    return LocalFSStateStore(str(tmp_path / "store"))


@pytest.fixture()
def mem_statestore():
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    return MemoryStateStore()

from batch_shipyard_tpu.state.base import (  # noqa: F401
    EntityExistsError,
    EtagMismatchError,
    LeaseHandle,
    LeaseLostError,
    NotFoundError,
    ObjectMeta,
    PreconditionFailedError,
    QueueMessage,
    StateStore,
)
from batch_shipyard_tpu.state.factory import create_statestore  # noqa: F401

"""KV-cache decode correctness: cached single-step decoding must
reproduce the full-forward teacher-forced argmax path exactly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference, transformer as tfm


@pytest.fixture(scope="module")
def setup():
    config = tfm.TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_head=16,
        d_ff=128, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    model = tfm.TransformerLM(config)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return config, model, params


def test_greedy_decode_matches_full_forward(setup):
    config, model, params = setup
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, 97, (2, 6)), jnp.int32)
    run, _ = inference.make_decoder(config, params, max_decode_len=32)
    out, _cache = run(prompt, 10, jax.random.PRNGKey(1))
    assert out.shape == (2, 16)
    np.testing.assert_array_equal(np.asarray(out[:, :6]),
                                  np.asarray(prompt))
    # Reference: greedy rollout via repeated full forwards (no cache).
    seq = prompt
    for _ in range(10):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_sampling_temperature_and_topk(setup):
    config, model, params = setup
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    run, _ = inference.make_decoder(config, params, max_decode_len=32)
    sampling = inference.SamplingConfig(temperature=1.0, top_k=5)
    out_a, _ = run(prompt, 8, jax.random.PRNGKey(7),
                   sampling=sampling)
    out_b, _ = run(prompt, 8, jax.random.PRNGKey(8),
                   sampling=sampling)
    assert out_a.shape == (1, 11)
    # Different keys should (overwhelmingly) give different samples.
    assert not np.array_equal(np.asarray(out_a), np.asarray(out_b))
    # Same key reproduces exactly.
    out_c, _ = run(prompt, 8, jax.random.PRNGKey(7),
                   sampling=sampling)
    np.testing.assert_array_equal(np.asarray(out_a),
                                  np.asarray(out_c))


def test_decode_respects_max_len(setup):
    config, model, params = setup
    run, dmodel = inference.make_decoder(config, params,
                                         max_decode_len=8)
    prompt = jnp.asarray([[5, 6]], jnp.int32)
    out, cache = run(prompt, 6, jax.random.PRNGKey(0))
    assert out.shape == (1, 8)
    # Cache index advanced exactly prompt+generated-1 writes... every
    # step writes once: prompt (2) + decode steps (5) = 7? The last
    # sampled token is never fed back. index == total forward calls.
    leaf = jax.tree_util.tree_leaves(
        {k: v for k, v in cache.items()})[0]
    assert leaf is not None


def test_multi_token_insert_matches_sequential(setup):
    """The batched prefill path (multi-token _decode_attend insert)
    must produce the same cache state and outputs as feeding the same
    tokens one step at a time — including a chunk inserted at a
    nonzero per-slot depth."""
    config, model, params = setup
    dconfig = inference.decode_config(config, max_decode_len=32)
    dmodel = tfm.TransformerLM(dconfig)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 7)), jnp.int32)

    # Sequential: one token per apply.
    cache_seq = inference.init_cache(dmodel, params, 2)
    outs = []
    for t in range(tokens.shape[1]):
        logits, mut = dmodel.apply(
            {"params": params, "cache": cache_seq},
            tokens[:, t:t + 1], positions=jnp.int32(t)[None],
            mutable=["cache"])
        cache_seq = mut["cache"]
        outs.append(logits[:, 0])
    seq_logits = jnp.stack(outs, axis=1)        # [B, T, vocab]

    # Batched: one multi-token apply (positions default to arange).
    cache_bat = inference.init_cache(dmodel, params, 2)
    bat_logits, mut = dmodel.apply(
        {"params": params, "cache": cache_bat}, tokens,
        mutable=["cache"])
    cache_bat = mut["cache"]
    np.testing.assert_allclose(
        np.asarray(bat_logits), np.asarray(seq_logits),
        rtol=2e-5, atol=2e-5)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(cache_seq),
            jax.tree_util.tree_leaves_with_path(cache_bat)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5,
            err_msg=str(pa))

    # Chunked continuation from depth 7: next 3 tokens in one chunk
    # vs one-at-a-time, on top of identical caches.
    more = jnp.asarray(rng.randint(0, 97, (2, 3)), jnp.int32)
    cache_a, cache_b = cache_seq, cache_bat
    for t in range(3):
        logits, mut = dmodel.apply(
            {"params": params, "cache": cache_a},
            more[:, t:t + 1], positions=jnp.int32(7 + t)[None],
            mutable=["cache"])
        cache_a = mut["cache"]
    last_seq = logits[:, 0]
    chunk_logits, mut = dmodel.apply(
        {"params": params, "cache": cache_b}, more,
        positions=jnp.arange(7, 10, dtype=jnp.int32),
        mutable=["cache"])
    np.testing.assert_allclose(
        np.asarray(chunk_logits[:, -1]), np.asarray(last_seq),
        rtol=2e-5, atol=2e-5)

#!/usr/bin/env python3
"""One-shot silicon proof pipeline (VERDICT r4 next #1).

The TPU relay has been wedged for three rounds; the moment it answers,
everything the rounds have been waiting to prove must happen in ONE
unattended pass, with no builder in the loop. tools/bench_retry.sh
invokes this script on the first successful probe; it:

  1. probe          — subprocess device probe with a hard timeout
                      (utils/util.probe_default_devices).
  2. kernel_checks  — tools/tpu_checks.py --write-marker: every Pallas
                      kernel (flash fwd/bwd, flash-ring, paged
                      attention, int8, fused norm, chunked
                      cross-entropy) vs its oracle ON THE CHIP,
                      results persisted as KERNEL_VALIDATION.json.
  3. flash_flip     — confirms ops/ring_attention.resolve_ring_impl
                      and ops/chunked_loss impl='auto' now resolve to
                      their Pallas paths (the marker is the flip: no
                      code edit).
  4. ring_collectives — async-DMA ring collective kernels
                      (ops/ring_collectives.py): bandwidth per message
                      size vs the lax collectives plus numeric parity,
                      remote-DMA ring when >1 chip answers, the
                      virtual-ring kernels on a single chip.
  5. tuning_ab      — bench.py --quick per parallel/tuning.py profile
                      (fresh subprocess each: XLA_FLAGS are read at
                      backend init); winner by throughput geomean
                      persisted as TUNING_SELECTED.json, which
                      bench.py auto-applies from then on.
  6. final_bench    — full bench.py under the winning profile; the
                      one-line JSON lands in BENCH_LATEST.json and
                      BENCH_DETAILS.json carries explicit per-workload
                      MFU%% (parallel/mfu.py).
  7. serving_speculative — speculative continuous-batching serving
                      (dense + paged KV): tokens/s, TTFT/TPOT, and
                      the measured draft acceptance rate per variant.
  8. checkpoint_overhead — zero-stall checkpointing proof: blocking
                      ms/save of the sync full-durability save vs the
                      async double-buffered pipeline on a synthetic
                      large pytree (workloads/checkpoint.py).
  9. goodput        — ML-productivity goodput decomposition of the
                      bench pool's event log (goodput/accounting.py):
                      goodput_ratio plus badput seconds per category,
                      persisted as GOODPUT_REPORT.json.
 10. compile_warm   — warm-start compilation proof: cold vs warm
                      persistent-compile-cache wall time for the
                      transformer train step in fresh subprocesses,
                      plus the AOT-precompile first-step spike check
                      (batch_shipyard_tpu/compilecache/).
 11. chaos_drill    — self-healing proof: a seeded fault schedule
                      (wedge, mid-run kill, node preemption,
                      heartbeat blackout, store faults) replayed
                      against a fakepod pool via tools/chaos_drill.py
                      with every recovery invariant asserted (all
                      tasks complete exactly once, no orphaned
                      coordination state, goodput partition exact).

Every phase's outcome is recorded in SILICON_PROOF.json; --dry-run
writes the complete report skeleton on CPU (each phase records the
exact command it would run) so the pipeline itself is CI-testable.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

PROBE_TIMEOUT = 240
CHECKS_TIMEOUT = 1800
BENCH_QUICK_TIMEOUT = 1800
BENCH_FULL_TIMEOUT = 2400


def _run(cmd: list[str], timeout: int, env: dict | None = None,
         log_path: pathlib.Path | None = None) -> tuple[int, str]:
    """Run a child with a hard timeout, capturing combined output."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    try:
        proc = subprocess.run(
            cmd, cwd=str(REPO_ROOT), env=full_env,
            capture_output=True, timeout=timeout, text=True)
        out = proc.stdout + proc.stderr
        rc = proc.returncode
    except subprocess.TimeoutExpired as exc:
        out = ((exc.stdout or b"").decode(errors="replace")
               if isinstance(exc.stdout, bytes) else (exc.stdout or "")
               ) + f"\nTIMEOUT after {timeout}s"
        rc = 124
    if log_path is not None:
        log_path.write_text(out, encoding="utf-8")
    return rc, out


class Pipeline:
    def __init__(self, out_dir: pathlib.Path, dry_run: bool,
                 skip_tuning: bool):
        self.out = out_dir
        self.dry = dry_run
        self.skip_tuning = skip_tuning
        self.marker = self.out / "KERNEL_VALIDATION.json"
        self.phases: list[dict] = []
        # Children must consult OUR marker (tests point out_dir at a
        # tmp dir; production uses the repo root ops read by default).
        self.child_env = {"SHIPYARD_KERNEL_VALIDATION":
                          str(self.marker)}

    def record(self, name: str, status: str, **extra) -> dict:
        entry = {"phase": name, "status": status, **extra}
        self.phases.append(entry)
        print(f"[silicon-proof] {name}: {status} "
              + json.dumps({k: v for k, v in extra.items()
                            if k != "output_tail"}))
        return entry

    # -- phases ----------------------------------------------------
    def probe(self) -> bool:
        cmd_doc = "probe_default_devices(timeout=%d)" % PROBE_TIMEOUT
        if self.dry:
            self.record("probe", "dry_run", command=cmd_doc)
            return True
        from batch_shipyard_tpu.utils.util import probe_default_devices
        count, reason = probe_default_devices(timeout=PROBE_TIMEOUT)
        if reason is not None or count < 1:
            self.record("probe", "failed",
                        error=reason or "no devices")
            return False
        self.record("probe", "ok", device_count=count)
        return True

    def kernel_checks(self) -> dict:
        cmd = [sys.executable, "tools/tpu_checks.py",
               "--write-marker", str(self.marker)]
        if self.dry:
            self.record("kernel_checks", "dry_run",
                        command=" ".join(cmd))
            return {}
        rc, out = _run(cmd, CHECKS_TIMEOUT,
                       log_path=self.out / "TPU_CHECKS_r05.txt")
        try:
            with open(self.marker, encoding="utf-8") as fh:
                results = json.load(fh)
        except (OSError, ValueError):
            results = {}
        self.record(
            "kernel_checks", "ok" if rc == 0 else "partial",
            rc=rc, results={k: v.get("ok") for k, v in
                            results.items()},
            output_tail=out[-2000:])
        return results

    def flash_flip(self, results: dict) -> None:
        if self.dry:
            self.record(
                "flash_flip", "dry_run",
                note="resolve_ring_impl('auto') + chunked-loss auto "
                     "re-checked in a TPU subprocess once the marker "
                     "exists")
            return
        # Resolution must be observed on the TPU backend — a fresh
        # subprocess, exactly as a user training run would see it.
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from batch_shipyard_tpu.ops import ring_attention as r\n"
            "from batch_shipyard_tpu.ops import kernel_select as ks\n"
            "print('ring=' + r.resolve_ring_impl('auto'))\n"
            "print('xent=' + ks.resolve_auto('chunked_cross_entropy'"
            ", pallas_impl='pallas'))\n" % str(REPO_ROOT))
        rc, out = _run([sys.executable, "-c", code], PROBE_TIMEOUT,
                       env=self.child_env)
        ring = "flash" if "ring=flash" in out else "xla"
        xent = "pallas" if "xent=pallas" in out else "xla"
        expect_ring = bool(results.get("flash_ring", {}).get("ok"))
        expect_xent = bool(
            results.get("chunked_cross_entropy", {}).get("ok"))
        ok = (rc == 0
              and (ring == "flash") == expect_ring
              and (xent == "pallas") == expect_xent)
        self.record("flash_flip", "ok" if ok else "failed",
                    ring_impl=ring, chunked_xent_impl=xent,
                    rc=rc, output_tail=out[-500:])

    def ring_collectives(self) -> None:
        """Async-DMA ring collective kernels
        (ops/ring_collectives.py) via bench.py's ring_collectives
        workload: per-size bandwidth rows plus a numeric parity flag
        against the lax collectives. Runs the remote-DMA shard_map
        ring when more than one chip answers, the virtual-ring
        kernels (same Mosaic DMA/semaphore lowering, no ICI) on a
        single chip — `mode` records which. The dry-run skeleton
        names every metric and carries the explicit
        accelerator-unreachable marker tools/benchgen.py renders."""
        details_path = self.out / "RING_COLLECTIVES_DETAILS.json"
        cmd = [sys.executable, "bench.py", "--workloads",
               "ring_collectives", "--details-out",
               str(details_path)]
        metric_keys = ("mode", "ring", "chips", "numeric_ok",
                       "best_all_gather_gbps",
                       "best_reduce_scatter_gbps")
        if self.dry:
            self.record(
                "ring_collectives", "dry_run",
                command=" ".join(cmd),
                note="accelerator unreachable — dry-run skeleton",
                metrics={k: None for k in metric_keys})
            return
        rc, out = _run(cmd, BENCH_QUICK_TIMEOUT, env=self.child_env)
        try:
            with open(details_path, encoding="utf-8") as fh:
                det = json.load(fh)
        except (OSError, ValueError):
            det = {}
        rep = det.get("ring_collectives") or {}
        if "error" in rep:
            summary = {"error": rep["error"]}
        else:
            summary = {k: rep.get(k) for k in metric_keys}
        ok = (rc == 0 and "error" not in summary
              and summary.get("numeric_ok") is True)
        self.record("ring_collectives", "ok" if ok else "failed",
                    rc=rc, metrics=summary, output_tail=out[-800:])

    def tuning_ab(self) -> str | None:
        from batch_shipyard_tpu.parallel.tuning import PROFILES
        plan = {
            profile: (f"SHIPYARD_XLA_TUNING={profile} {sys.executable}"
                      f" bench.py --quick --workloads "
                      f"resnet,transformer --details-out "
                      f"{self.out}/tuning_{profile}.json")
            for profile in PROFILES
        }
        if self.dry or self.skip_tuning:
            self.record("tuning_ab",
                        "dry_run" if self.dry else "skipped",
                        plan=plan)
            return None
        measurements: dict = {}
        for profile in PROFILES:
            details_path = self.out / f"tuning_{profile}.json"
            rc, out = _run(
                [sys.executable, "bench.py", "--quick", "--workloads",
                 "resnet,transformer", "--details-out",
                 str(details_path)],
                BENCH_QUICK_TIMEOUT,
                env={**self.child_env,
                     "SHIPYARD_XLA_TUNING": profile})
            entry: dict = {"rc": rc}
            try:
                with open(details_path, encoding="utf-8") as fh:
                    det = json.load(fh)
                entry["resnet_img_s"] = det.get("resnet50", {}).get(
                    "images_per_sec_per_chip")
                entry["transformer_tok_s"] = det.get(
                    "transformer", {}).get("tokens_per_sec_per_chip")
            except (OSError, ValueError):
                entry["error"] = out[-400:]
            measurements[profile] = entry

        def score(m: dict) -> float:
            r = m.get("resnet_img_s") or 0.0
            t = m.get("transformer_tok_s") or 0.0
            return (r * t) ** 0.5 if r and t else max(r, t)

        winner = max(measurements, key=lambda p:
                     score(measurements[p]))
        if score(measurements[winner]) <= 0:
            self.record("tuning_ab", "failed",
                        measurements=measurements)
            return None
        selected = {"winner": winner, "measurements": measurements,
                    "selected_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        with open(self.out / "TUNING_SELECTED.json", "w",
                  encoding="utf-8") as fh:
            json.dump(selected, fh, indent=2)
        self.record("tuning_ab", "ok", winner=winner,
                    measurements=measurements)
        return winner

    def final_bench(self, winner: str | None) -> None:
        env = dict(self.child_env)
        if winner:
            env["SHIPYARD_XLA_TUNING"] = winner
        cmd = [sys.executable, "bench.py", "--details-out",
               str(self.out / "BENCH_DETAILS.json")]
        if self.dry:
            self.record("final_bench", "dry_run",
                        command=" ".join(cmd))
            return
        rc, out = _run(cmd, BENCH_FULL_TIMEOUT, env=env)
        last = out.strip().splitlines()[-1] if out.strip() else ""
        parsed = None
        try:
            parsed = json.loads(last)
            with open(self.out / "BENCH_LATEST.json", "w",
                      encoding="utf-8") as fh:
                fh.write(last + "\n")
        except ValueError:
            pass
        mfu = {}
        try:
            with open(self.out / "BENCH_DETAILS.json",
                      encoding="utf-8") as fh:
                det = json.load(fh)
            for k in ("resnet50", "transformer", "transformer_int8"):
                if isinstance(det.get(k), dict):
                    mfu[k] = det[k].get("mfu_pct")
        except (OSError, ValueError):
            pass
        self.record("final_bench",
                    "ok" if rc == 0 and parsed else "failed",
                    rc=rc, headline=parsed, mfu_pct=mfu,
                    output_tail=out[-1000:])
        # Regenerate the measured-numbers docs page from the fresh
        # artifacts (docs/26-benchmarks.md cannot rot by design).
        _run([sys.executable, "tools/benchgen.py",
              "--artifacts-dir", str(self.out)], 120)

    def serving_speculative(self) -> None:
        """Speculative continuous-batching serving (dense + paged KV):
        per-variant tokens/s, TTFT/TPOT p50, and the engine's measured
        acceptance rate, via bench.py's serving_speculative workload
        (models/serving.py draft/verify engine steps)."""
        details_path = self.out / "SPEC_SERVING_DETAILS.json"
        cmd = [sys.executable, "bench.py", "--workloads",
               "serving_speculative", "--details-out",
               str(details_path)]
        metric_keys = ("tokens_per_second", "ttft_ms_p50",
                       "tpot_ms_p50", "acceptance_rate")
        if self.dry:
            self.record(
                "serving_speculative", "dry_run",
                command=" ".join(cmd),
                metrics={variant: {k: None for k in metric_keys}
                         for variant in ("dense", "paged")})
            return
        rc, out = _run(cmd, BENCH_QUICK_TIMEOUT, env=self.child_env)
        summary: dict = {}
        try:
            with open(details_path, encoding="utf-8") as fh:
                det = json.load(fh)
        except (OSError, ValueError):
            det = {}
        for variant, key in (("dense", "serving_speculative"),
                             ("paged", "serving_speculative_paged")):
            rep = det.get(key) or {}
            if "error" in rep:
                summary[variant] = {"error": rep["error"]}
                continue
            spec = rep.get("speculative") or {}
            summary[variant] = {
                "tokens_per_second": rep.get("tokens_per_second"),
                "ttft_ms_p50": (rep.get("ttft_ms") or {}).get("p50"),
                "tpot_ms_p50": (rep.get("tpot_ms") or {}).get("p50"),
                "acceptance_rate": spec.get("acceptance_rate"),
            }
        ok = (rc == 0 and summary
              and all("error" not in v for v in summary.values()))
        self.record("serving_speculative",
                    "ok" if ok else "failed", rc=rc,
                    metrics=summary, output_tail=out[-800:])

    def checkpoint_overhead(self) -> None:
        """Sync vs async blocking ms/save (bench.py's
        checkpoint_overhead workload): the training loop's measured
        stall per checkpoint, before and after the async
        double-buffered save pipeline. The dry-run skeleton names
        every metric so report consumers bind to the shape on CPU."""
        details_path = self.out / "CKPT_OVERHEAD_DETAILS.json"
        cmd = [sys.executable, "bench.py", "--workloads",
               "checkpoint_overhead", "--details-out",
               str(details_path)]
        metric_keys = ("sync_blocking_ms_per_save",
                       "async_blocking_ms_per_save",
                       "blocking_speedup", "payload_mb", "saves")
        if self.dry:
            self.record("checkpoint_overhead", "dry_run",
                        command=" ".join(cmd),
                        metrics={k: None for k in metric_keys})
            return
        rc, out = _run(cmd, BENCH_QUICK_TIMEOUT, env=self.child_env)
        try:
            with open(details_path, encoding="utf-8") as fh:
                det = json.load(fh)
        except (OSError, ValueError):
            det = {}
        rep = det.get("checkpoint_overhead") or {}
        if "error" in rep:
            summary = {"error": rep["error"]}
        else:
            summary = {k: rep.get(k) for k in metric_keys}
        ok = (rc == 0 and "error" not in summary
              and summary.get("sync_blocking_ms_per_save")
              is not None)
        self.record("checkpoint_overhead",
                    "ok" if ok else "failed", rc=rc,
                    metrics=summary, output_tail=out[-800:])

    def compile_warm(self) -> None:
        """Cold vs warm compile wall time through the persistent
        compilation cache (bench.py's compile_warm workload): run 1
        compiles the transformer train step cold into a fresh cache
        dir, run 2 deserializes warm with AOT precompile — the per
        node, per-restart badput that pool-wide cache seeding
        removes. The dry-run skeleton names every metric."""
        details_path = self.out / "COMPILE_WARM_DETAILS.json"
        cmd = [sys.executable, "bench.py", "--workloads",
               "compile_warm", "--details-out", str(details_path)]
        metric_keys = ("cold_ms", "warm_ms", "speedup", "cache_hits",
                       "aot_first_step_ms", "steady_step_ms")
        if self.dry:
            self.record("compile_warm", "dry_run",
                        command=" ".join(cmd),
                        metrics={k: None for k in metric_keys})
            return
        rc, out = _run(cmd, BENCH_QUICK_TIMEOUT, env=self.child_env)
        try:
            with open(details_path, encoding="utf-8") as fh:
                det = json.load(fh)
        except (OSError, ValueError):
            det = {}
        rep = det.get("compile_warm") or {}
        if "error" in rep:
            summary = {"error": rep["error"]}
        else:
            summary = {k: rep.get(k) for k in metric_keys}
        ok = (rc == 0 and "error" not in summary
              and summary.get("cold_ms") is not None
              and summary.get("warm_ms") is not None
              and summary["warm_ms"] < summary["cold_ms"])
        self.record("compile_warm", "ok" if ok else "failed", rc=rc,
                    metrics=summary, output_tail=out[-800:])

    def goodput(self) -> None:
        """Decompose whatever goodput events the bench run's state
        store accumulated into the paper's availability x resource x
        program legs. The dry-run skeleton names goodput_ratio, each
        decomposition leg, and every badput category so report
        consumers (tools/benchgen.py) can bind to the shape on CPU."""
        from batch_shipyard_tpu.goodput import accounting
        skeleton = {
            "goodput_ratio": None,
            "availability_goodput": None,
            "resource_goodput": None,
            "program_goodput": None,
            "badput_seconds": {category: None for category in
                               accounting.BADPUT_CATEGORIES},
            "overlapped_seconds": {category: None for category in
                                   accounting.OVERLAPPED_CATEGORIES},
        }
        cmd = (f"{sys.executable} -m batch_shipyard_tpu.cli.main "
               f"goodput pool --raw")
        if self.dry:
            self.record("goodput", "dry_run", command=cmd,
                        metrics=skeleton)
            return
        try:
            from batch_shipyard_tpu.state.memory import (
                MemoryStateStore)
            store_path = os.environ.get("SHIPYARD_BENCH_STORE")
            if store_path:
                from batch_shipyard_tpu.state.localfs import (
                    LocalFSStateStore)
                store = LocalFSStateStore(store_path)
            else:
                # No orchestrated pool in this bench run: nothing to
                # account — record the honest empty decomposition.
                store = MemoryStateStore()
            report = accounting.fleet_report(store)
            with open(self.out / "GOODPUT_REPORT.json", "w",
                      encoding="utf-8") as fh:
                json.dump(report, fh, indent=2)
            self.record(
                "goodput",
                "ok" if report["wall_seconds"] > 0 else "no_events",
                goodput_ratio=report["goodput_ratio"],
                badput_seconds=report["badput_seconds"])
        except Exception as exc:  # noqa: BLE001 - report, don't die
            self.record("goodput", "failed", error=str(exc))

    def chaos_drill(self) -> None:
        """Self-healing proof (chaos/): replay a seeded fault
        schedule over a fakepod pool and assert the recovery
        invariants. Pure CPU — real NodeAgent threads, no
        accelerator — so the same drill that gates CI also runs on
        the pod to prove recovery under real substrate timing. The
        dry-run skeleton names every invariant benchgen binds to."""
        details_path = self.out / "CHAOS_DRILL_DETAILS.json"
        cmd = [sys.executable, "tools/chaos_drill.py",
               "--seeds", "7",
               "--report-out", str(details_path)]
        invariant_keys = ("tasks", "orphaned_gang_rows",
                          "queue_depth", "retries",
                          "backoff_seconds")
        if self.dry:
            self.record("chaos_drill", "dry_run",
                        command=" ".join(cmd),
                        metrics={"determinism": None,
                                 "injections_applied": None,
                                 "invariants": {k: None for k in
                                                invariant_keys}})
            return
        rc, out = _run(cmd, BENCH_QUICK_TIMEOUT, env=self.child_env)
        try:
            with open(details_path, encoding="utf-8") as fh:
                det = json.load(fh)
        except (OSError, ValueError):
            det = {}
        scenarios = det.get("scenarios") or [{}]
        first = scenarios[0]
        summary = {
            "determinism": first.get("determinism"),
            "injections_applied": first.get("injections_applied"),
            "invariants": {k: first.get("invariants", {}).get(k)
                           for k in invariant_keys},
        }
        if first.get("error"):
            summary["error"] = first["error"]
        ok = rc == 0 and det.get("ok") is True
        self.record("chaos_drill", "ok" if ok else "failed", rc=rc,
                    metrics=summary, output_tail=out[-800:])

    # -- driver ----------------------------------------------------
    def run(self) -> int:
        started = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        ok = self.probe()
        results: dict = {}
        if ok:
            results = self.kernel_checks()
            self.flash_flip(results)
            self.ring_collectives()
            winner = self.tuning_ab()
            self.final_bench(winner)
            self.serving_speculative()
            self.checkpoint_overhead()
            self.goodput()
            self.compile_warm()
            self.chaos_drill()
        report = {
            "started_at": started,
            "finished_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "dry_run": self.dry,
            "phases": self.phases,
        }
        with open(self.out / "SILICON_PROOF.json", "w",
                  encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
        bad = [p for p in self.phases
               if p["status"] in ("failed", "partial")]
        print(f"[silicon-proof] report: "
              f"{self.out / 'SILICON_PROOF.json'} "
              f"({len(self.phases)} phases, {len(bad)} not ok)")
        return 1 if bad else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dry-run", action="store_true",
                        help="write the full report skeleton without "
                        "touching an accelerator (CI path)")
    parser.add_argument("--out-dir", default=str(REPO_ROOT),
                        help="where reports land (default: repo "
                        "root)")
    parser.add_argument("--skip-tuning", action="store_true",
                        help="skip the profile A/B (bench under the "
                        "default profile only)")
    args = parser.parse_args(argv)
    out_dir = pathlib.Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    return Pipeline(out_dir, args.dry_run, args.skip_tuning).run()


if __name__ == "__main__":
    sys.exit(main())

"""Autoregressive inference: KV-cache decode + sampling.

The framework's serving-side counterpart to the training path
(ROADMAP item; the reference had no inference story at all). Design:

  - prefill: ONE jitted full-sequence forward over the prompt writing
    all KV-cache rows in a single MXU-batched pass (the multi-token
    insert path of transformer._decode_attend) — prefill cost is one
    forward, not T_prompt sequential micro-steps;
  - decode: one token per step through the transformer's decode mode
    (flax 'cache' collection holding per-layer K/V + write index),
    inside a single jitted lax.scan — no per-token Python dispatch;
  - sampling: greedy, temperature, and top-k, driven by a jax PRNG key.

Works on CPU/TPU and under dp sharding (batch dim); cache lives on
device for the whole generation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from batch_shipyard_tpu.models import transformer as tfm


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => full distribution


def decode_config(config: tfm.TransformerConfig,
                  max_decode_len: int) -> tfm.TransformerConfig:
    return dataclasses.replace(
        config, decode=True, max_decode_len=max_decode_len,
        attention_fn=None, remat=False)


def init_cache(model: tfm.TransformerLM, params, batch_size: int):
    """Materialize an empty KV cache pytree for the decode model.

    model.init runs a forward pass, which WRITES the dummy token into
    slot 0 and bumps the index — zero everything so the cache starts
    truly empty."""
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
        positions=jnp.zeros((1,), jnp.int32))
    return jax.tree_util.tree_map(jnp.zeros_like, variables["cache"])


def _sample(logits, key, sampling: SamplingConfig):
    """logits: [B, vocab] fp32 -> token ids [B]."""
    if sampling.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / sampling.temperature
    if sampling.top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, sampling.top_k)
        cutoff = top_vals[:, -1][:, None]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(
        jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "model", "num_tokens", "sampling"))
def generate(model: tfm.TransformerLM, params, cache, prompt,
             num_tokens: int, key,
             sampling: SamplingConfig = SamplingConfig()):
    """Generate num_tokens continuations of prompt [B, T_prompt].

    Returns (tokens [B, T_prompt + num_tokens], cache). The whole
    prefill + decode runs inside one jit; per-token work is a lax.scan
    step feeding the KV cache.
    """
    batch, prompt_len = prompt.shape

    def step(carry, _):
        cache, token, pos, key = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token,
            positions=pos[None], mutable=["cache"])
        key, sample_key = jax.random.split(key)
        next_token = _sample(logits[:, 0].astype(jnp.float32),
                             sample_key, sampling)
        return ((mutated["cache"], next_token[:, None], pos + 1, key),
                next_token)

    # Prefill: ONE full-sequence forward through the multi-token
    # cache-insert path (transformer._decode_attend seq > 1) — all
    # prompt K/V land in the cache in a single MXU-batched pass
    # instead of a T_prompt-step scan. Only the last position's
    # logits are needed, so return_hidden + a [B, d] x [d, vocab]
    # matmul avoids materializing [B, T, vocab] fp32 logits.
    hidden, mutated = model.apply(
        {"params": params, "cache": cache}, prompt,
        return_hidden=True, mutable=["cache"])
    cache = mutated["cache"]
    pos = jnp.int32(prompt_len)
    embedding = params["embed"]["embedding"]
    last_logits = jnp.dot(hidden[:, -1].astype(jnp.float32),
                          embedding.astype(jnp.float32).T)
    key, sample_key = jax.random.split(key)
    first = _sample(last_logits, sample_key, sampling)
    (cache, _tok, _pos, _key), generated = jax.lax.scan(
        step, (cache, first[:, None], pos, key), None,
        length=num_tokens - 1)
    tokens = jnp.concatenate(
        [prompt, first[:, None],
         jnp.moveaxis(generated, 0, 1)], axis=1)
    return tokens, cache


def make_decoder(config: tfm.TransformerConfig, params,
                 max_decode_len: int):
    """Convenience: (generate_fn, model) bound to a decode-mode model
    sharing training params."""
    dconfig = decode_config(config, max_decode_len)
    model = tfm.TransformerLM(dconfig)

    def run(prompt, num_tokens, key,
            sampling: SamplingConfig = SamplingConfig()):
        cache = init_cache(model, params, prompt.shape[0])
        return generate(model, params, cache, prompt, num_tokens, key,
                        sampling)

    return run, model

"""CLI + fleet end-to-end: the full `pool add` -> `jobs add --tail`
flow through the click entrypoint on a fake pool (the reference's
minimum end-to-end slice, SURVEY.md section 7 step 3)."""

import json
import os

import pytest
import yaml
from click.testing import CliRunner

from batch_shipyard_tpu import fleet
from batch_shipyard_tpu.cli.main import cli
from batch_shipyard_tpu.state import factory as state_factory


@pytest.fixture()
def configdir(tmp_path):
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"docker_images": []}},
        "pool": {"pool_specification": {
            "id": "clipool", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-8"},
            "max_wait_time_seconds": 30}},
        "jobs": {"job_specifications": [{
            "id": "clijob",
            "tasks": [{"command": "echo cli-works"}]}]},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    return str(tmp_path)


def test_cli_help():
    result = CliRunner().invoke(cli, ["--help"])
    assert result.exit_code == 0
    for group in ("pool", "jobs", "data", "diag"):
        assert group in result.output


def test_cli_pool_jobs_flow(configdir):
    runner = CliRunner()
    result = runner.invoke(
        cli, ["--configdir", configdir, "pool", "add"],
        catch_exceptions=False)
    assert result.exit_code == 0
    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "pool", "list"],
        catch_exceptions=False)
    assert result.exit_code == 0
    assert json.loads(result.output)["pools"][0]["id"] == "clipool"

    result = runner.invoke(
        cli, ["--configdir", configdir, "jobs", "add",
              "--tail", "stdout.txt"], catch_exceptions=False)
    assert result.exit_code == 0
    assert "cli-works" in result.output

    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "jobs", "tasks",
              "list", "clijob"], catch_exceptions=False)
    tasks = json.loads(result.output)["tasks"]
    assert tasks[0]["state"] == "completed"

    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "pool", "stats"],
        catch_exceptions=False)
    stats = json.loads(result.output)
    assert stats["tasks"]["completed"] == 1

    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "diag", "perf"],
        catch_exceptions=False)
    events = json.loads(result.output)["events"]
    assert any(e["event"] == "create.end" for e in events)

    result = runner.invoke(
        cli, ["--configdir", configdir, "data", "stream", "clijob",
              "task-00000"], catch_exceptions=False)
    assert "cli-works" in result.output

    result = runner.invoke(
        cli, ["--configdir", configdir, "pool", "del", "-y"],
        catch_exceptions=False)
    assert result.exit_code == 0


def test_cli_rejects_bad_config(tmp_path):
    with open(tmp_path / "pool.yaml", "w") as fh:
        yaml.safe_dump({"pool_specification": {"id": "x",
                                               "bogus": True}}, fh)
    with open(tmp_path / "credentials.yaml", "w") as fh:
        yaml.safe_dump({"credentials": {
            "storage": {"backend": "memory"}}}, fh)
    result = CliRunner().invoke(
        cli, ["--configdir", str(tmp_path), "pool", "add"])
    assert result.exit_code != 0
    assert "bogus" in str(result.exception or result.output)


def test_fs_bucket_mount_args(tmp_path):
    """gcs_buckets in fs.yaml render nodeprep gcsfuse mount commands
    (the RemoteFS-GCSFuse+Pool recipe surface)."""
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "fs": {"remote_fs": {
            "gcs_buckets": {"shared-data": {
                "bucket": "my-bucket",
                "mount_options": ["implicit-dirs", "file-mode=644"],
            }}}},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    result = CliRunner().invoke(
        cli, ["--configdir", str(tmp_path), "fs", "bucket",
              "mount-args", "shared-data"])
    assert result.exit_code == 0, result.output
    assert "gcsfuse --implicit-dirs -o file-mode=644 my-bucket " \
        "/mnt/shared-data" in result.output
    assert "mkdir -p /mnt/shared-data" in result.output
    missing = CliRunner().invoke(
        cli, ["--configdir", str(tmp_path), "fs", "bucket",
              "mount-args", "nope"])
    assert missing.exit_code != 0


def test_pool_exists_and_tasks_count(tmp_path):
    """`pool exists` exit semantics and `jobs tasks count` aggregation
    (reference shipyard.py pool exists / tasks count verbs)."""
    import yaml
    from click.testing import CliRunner
    from batch_shipyard_tpu.cli.main import cli
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"docker_images": []}},
        "pool": {"pool_specification": {
            "id": "clip", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}},
        "jobs": {"job_specifications": [{
            "id": "cj", "tasks": [{"command": "echo one"},
                                  {"command": "echo two"}]}]},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    runner = CliRunner()
    base = ["--configdir", str(tmp_path)]
    missing = runner.invoke(cli, base + ["pool", "exists"])
    assert missing.exit_code == 1, missing.output
    assert runner.invoke(
        cli, base + ["pool", "add"]).exit_code == 0
    present = runner.invoke(cli, base + ["pool", "exists"])
    assert present.exit_code == 0, present.output
    assert runner.invoke(
        cli, base + ["jobs", "add"]).exit_code == 0
    import time
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        out = runner.invoke(
            cli, base + ["--raw", "jobs", "tasks", "count", "cj"])
        assert out.exit_code == 0, out.output
        import json as json_mod
        counts = json_mod.loads(out.output)
        if counts["by_state"].get("completed") == 2:
            break
        time.sleep(0.5)
    assert counts["total"] == 2
    assert counts["by_state"] == {"completed": 2}


def test_tasks_count_unknown_job_errors(tmp_path):
    import yaml
    from click.testing import CliRunner
    from batch_shipyard_tpu.cli.main import cli
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "pool": {"pool_specification": {
            "id": "cx", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    out = CliRunner().invoke(
        cli, ["--configdir", str(tmp_path), "jobs", "tasks", "count",
              "ghost"])
    assert out.exit_code != 0
    assert "does not exist" in out.output


def test_data_ingress_cli_filters_unready_nodes(tmp_path, monkeypatch):
    """'data ingress' with a shared-fs spec only shards onto READY
    nodes (a booting/failed node must not receive transfer work)."""
    from batch_shipyard_tpu.data import movement
    src_dir = tmp_path / "payload"
    src_dir.mkdir()
    (src_dir / "x.bin").write_bytes(b"z" * 128)
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"files": [{
            "source": {"path": str(src_dir)},
            "destination": {"path": "/mnt/shared"}}]}},
        "pool": {"pool_specification": {
            "id": "ingp", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-16"},
            "max_wait_time_seconds": 30}},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    runner = CliRunner()
    base = ["--configdir", str(tmp_path)]
    assert runner.invoke(cli, base + ["pool", "add"]).exit_code == 0
    # Mark one node unready out-of-band.
    from batch_shipyard_tpu.state import names
    from batch_shipyard_tpu.state.localfs import LocalFSStateStore
    store = LocalFSStateStore(str(tmp_path / "store"))
    rows = list(store.query_entities(names.TABLE_NODES,
                                     partition_key="ingp"))
    store.merge_entity(names.TABLE_NODES, "ingp", rows[0]["_rk"],
                       {"state": "start_task_failed"})
    captured = {}

    def fake_ingress(store_, conf, pool_id=None, node_logins=None,
                     ssh_username="shipyard", ssh_private_key=None):
        captured["logins"] = node_logins
        return 0

    monkeypatch.setattr(movement, "ingress_data", fake_ingress)
    out = runner.invoke(cli, base + ["data", "ingress"])
    assert out.exit_code == 0, out.output
    login_ids = {n for n, _ip, _p in captured["logins"]}
    assert rows[0]["_rk"] not in login_ids
    assert len(login_ids) == len(rows) - 1


def test_cli_pool_nodes_operator_verbs(configdir):
    """The round-5 node verbs through the click layer on a fake
    pool: count/grls/ps answer, reboot/del mutate slice-granularly."""
    runner = CliRunner()
    r = runner.invoke(cli, ["--configdir", configdir, "pool", "add"],
                      catch_exceptions=False)
    assert r.exit_code == 0
    r = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "pool", "nodes",
              "count"], catch_exceptions=False)
    counts = json.loads(r.output)
    assert counts["total"] == 2  # v5litepod-8 = 2 workers
    r = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "pool", "nodes",
              "grls"], catch_exceptions=False)
    grls = json.loads(r.output)["remote_login"]
    assert len(grls) == 2 and all(g["ip"] for g in grls)
    # Each CLI invocation builds a fresh fake substrate; agents are
    # revived via ensure_attached and may need a beat before their
    # heartbeats mark them ready — poll briefly.
    import time as time_mod
    # Budget exceeds one full nodes_ps reply timeout (30s) so the
    # retry actually gets used on a slow machine.
    deadline = time_mod.monotonic() + 70
    while True:
        r = runner.invoke(
            cli, ["--configdir", configdir, "--raw", "pool", "nodes",
                  "ps"], catch_exceptions=False)
        ps = json.loads(r.output)["nodes"]
        assert len(ps) == 2
        if all("running_tasks" in n for n in ps):
            break
        assert time_mod.monotonic() < deadline, ps
        time_mod.sleep(0.2)
    assert all(n["running_tasks"] == [] for n in ps)
    node_id = grls[0]["node_id"]
    r = runner.invoke(
        cli, ["--configdir", configdir, "pool", "nodes", "reboot",
              node_id, "-y"], catch_exceptions=False)
    assert r.exit_code == 0 and "recreated_slice" in r.output
    # Wait for the rebooted slice's agents to finish booting: a boot
    # thread still inside start() would resurrect the node row (via
    # its initial upsert) after the del below tears it down.
    deadline = time_mod.monotonic() + 30
    while True:
        r = runner.invoke(
            cli, ["--configdir", configdir, "--raw", "pool", "nodes",
                  "count"], catch_exceptions=False)
        by_state = json.loads(r.output)["by_state"]
        if by_state.get("idle", 0) + by_state.get("running", 0) == 2:
            break
        assert time_mod.monotonic() < deadline, by_state
        time_mod.sleep(0.2)
    r = runner.invoke(
        cli, ["--configdir", configdir, "pool", "nodes", "del",
              node_id, "-y"], catch_exceptions=False)
    assert r.exit_code == 0 and "deallocated_slice" in r.output
    r = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "pool", "nodes",
              "count"], catch_exceptions=False)
    assert json.loads(r.output)["total"] == 0  # single slice gone

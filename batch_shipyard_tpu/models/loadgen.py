"""Poisson-arrival load generator for the serving front end.

Measures what continuous-batching engines are judged by: TTFT and
TPOT percentiles under concurrent load, plus aggregate tokens/sec —
the serving benchmark the reference's recipes-as-acceptance strategy
(SURVEY.md section 4) implies but never had an ML engine to apply to.
stdlib-only: urllib for transport, threads for in-flight requests,
random.Random(seed) for reproducible arrivals.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence, Union

from batch_shipyard_tpu.trace.histogram import LatencyHistogram
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def _post_generate(base_url: str, payload: dict,
                   timeout: float) -> dict:
    req = urllib.request.Request(
        f"{base_url}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def run_load(base_url: Union[str, Sequence[str]],
             num_requests: int,
             rate_hz: float = 8.0,
             prompt_len: tuple[int, int] = (4, 32),
             max_new_tokens: tuple[int, int] = (8, 32),
             vocab_size: int = 97,
             seed: int = 0,
             eos_id: Optional[int] = None,
             request_timeout: float = 300.0) -> dict:
    """Fire ``num_requests`` at Poisson arrivals of ``rate_hz`` and
    return the latency report: TTFT/TPOT/latency p50/p90/p99 computed
    from MERGED per-replica fixed-log-bucket histograms
    (trace/histogram.py — the same aggregation rule the router and
    heimdall use, so bench numbers and fleet dashboards agree),
    tokens/sec, and the raw mergeable histograms.

    ``base_url`` may be a single URL or a list of replica URLs (a
    serving fleet — one server task per pool node); requests then
    round-robin across replicas and the report adds a per-replica
    completion breakdown."""
    urls = ([base_url] if isinstance(base_url, str)
            else list(base_url))
    rng = random.Random(seed)
    results: list[Optional[dict]] = [None] * num_requests
    errors: list[Optional[str]] = [None] * num_requests
    threads = []

    def _one(k: int, url: str, payload: dict) -> None:
        try:
            result = _post_generate(url, payload, request_timeout)
            result["_replica"] = url
            results[k] = result
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            errors[k] = str(exc)

    started = time.perf_counter()
    for k in range(num_requests):
        plen = rng.randint(*prompt_len)
        payload = {
            "request_id": f"load-{seed}-{k}",
            "prompt": [rng.randrange(vocab_size) for _ in range(plen)],
            "max_new_tokens": rng.randint(*max_new_tokens),
        }
        if eos_id is not None:
            payload["eos_id"] = eos_id
        thread = threading.Thread(
            target=_one, args=(k, urls[k % len(urls)], payload),
            daemon=True)
        thread.start()
        threads.append(thread)
        if k < num_requests - 1:
            time.sleep(rng.expovariate(rate_hz))
    for thread in threads:
        thread.join(request_timeout)
    elapsed = time.perf_counter() - started
    done = [r for r in results if r is not None]
    failed = [e for e in errors if e is not None]
    tokens = sum(r["num_tokens"] for r in done)
    # One histogram per (metric, replica), merged for the report:
    # this is the exact aggregation a fleet of independent replicas
    # supports (percentiles of pooled bucket counts), as opposed to
    # averaging per-replica percentiles or reporting means.
    per_replica: dict[str, dict[str, LatencyHistogram]] = {
        metric: {url: LatencyHistogram() for url in urls}
        for metric in ("ttft_ms", "tpot_ms", "latency_ms")}
    for r in done:
        for metric in ("ttft_ms", "tpot_ms", "latency_ms"):
            per_replica[metric][r["_replica"]].observe(r[metric])
    merged = {metric: LatencyHistogram.merged(hists.values())
              for metric, hists in per_replica.items()}
    report = {
        "num_requests": num_requests,
        "completed": len(done),
        "failed": len(failed),
        "offered_rate_hz": rate_hz,
        "elapsed_seconds": elapsed,
        "requests_per_second": len(done) / elapsed if elapsed else 0.0,
        "tokens_per_second": tokens / elapsed if elapsed else 0.0,
        "generated_tokens": tokens,
        "ttft_ms": merged["ttft_ms"].percentiles((50, 90, 99)),
        "tpot_ms": merged["tpot_ms"].percentiles((50, 90, 99)),
        "latency_ms": merged["latency_ms"].percentiles((50, 90, 99)),
        "ttft_hist": merged["ttft_ms"].to_dict(),
        "tpot_hist": merged["tpot_ms"].to_dict(),
    }
    if len(urls) > 1:
        by_replica: dict[str, int] = {}
        for r in done:
            by_replica[r["_replica"]] = by_replica.get(
                r["_replica"], 0) + 1
        report["replicas"] = len(urls)
        report["completed_by_replica"] = by_replica
    if failed:
        report["errors"] = failed[:8]
    return report


def main() -> int:
    """Standalone benchmark CLI against running server(s):

        python -m batch_shipyard_tpu.models.loadgen \\
            http://node0:8900 http://node1:8900 \\
            --num 128 --rate 32 --report fleet_report.json
    """
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("urls", nargs="+",
                        help="Serving front end base URL(s)")
    parser.add_argument("--num", type=int, default=64)
    parser.add_argument("--rate", type=float, default=8.0)
    parser.add_argument("--prompt-len", type=int, nargs=2,
                        default=(4, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--gen-tokens", type=int, nargs=2,
                        default=(8, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--report", default=None,
                        help="Also write the JSON report here")
    args = parser.parse_args()
    report = run_load(
        args.urls, args.num, rate_hz=args.rate,
        prompt_len=tuple(args.prompt_len),
        max_new_tokens=tuple(args.gen_tokens),
        vocab_size=args.vocab, seed=args.seed)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
    print(json.dumps(report))
    return 1 if report["failed"] else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

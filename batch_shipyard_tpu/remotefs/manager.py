"""RemoteFS: standalone shared-filesystem clusters for pools.

Reference analog: convoy/remotefs.py (2040 LoC — managed disks, NFS or
GlusterFS storage-cluster VMs with mdadm RAID-0 via
shipyard_remotefs_bootstrap.sh, mount-args generation for compute
pools :56) and scripts/shipyard_remotefs_bootstrap.sh.

TPU-native mapping: the common shared-FS for TPU pods is either (a) a
GCS bucket via gcsfuse (serverless, preferred — replaces most
GlusterFS use), or (b) an NFS server VM with striped persistent disks
(the direct remotefs analog). This module keeps cluster records in the
state store, generates the NFS server bootstrap script + fstab mount
args for pool nodes, and provisions the server VM through gcloud when
available (gated; records/plans always work for tests).
"""

from __future__ import annotations

from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_TABLE = names.TABLE_REMOTEFS
_NODES_TABLE = names.TABLE_REMOTEFS_NODES


def create_storage_cluster_record(
        store: StateStore, cluster_id: str, fs_type: str = "nfs",
        disk_count: int = 2, disk_size_gb: int = 256,
        disk_type: str = "pd-ssd", vm_size: str = "n2-standard-8",
        export_path: str = "/export/shipyard") -> dict:
    """Register a storage cluster (create_storage_cluster :623 analog;
    actual VM provisioning is provision_nfs_server)."""
    record = {
        "fs_type": fs_type, "disk_count": disk_count,
        "disk_size_gb": disk_size_gb, "disk_type": disk_type,
        "vm_size": vm_size, "export_path": export_path,
        "state": "defined",
        "created_at": util.datetime_utcnow_iso(),
    }
    try:
        store.insert_entity(_TABLE, "remotefs", cluster_id, record)
    except EntityExistsError:
        raise ValueError(f"storage cluster {cluster_id} exists")
    return record


def get_storage_cluster(store: StateStore, cluster_id: str) -> dict:
    try:
        return store.get_entity(_TABLE, "remotefs", cluster_id)
    except NotFoundError:
        raise ValueError(f"storage cluster {cluster_id} not found")


def delete_storage_cluster(store: StateStore, cluster_id: str) -> None:
    get_storage_cluster(store, cluster_id)
    for row in list(store.query_entities(_NODES_TABLE,
                                         partition_key=cluster_id)):
        store.delete_entity(_NODES_TABLE, cluster_id, row["_rk"])
    store.delete_entity(_TABLE, "remotefs", cluster_id)


def expand_storage_cluster(store: StateStore, cluster_id: str,
                           additional_disks: int) -> dict:
    """Record additional data disks (expand_storage_cluster :1171
    analog; on a live server this triggers mdadm --grow via ssh)."""
    cluster = get_storage_cluster(store, cluster_id)
    store.merge_entity(_TABLE, "remotefs", cluster_id, {
        "disk_count": int(cluster["disk_count"]) + additional_disks},
        if_match=cluster["_etag"])
    return get_storage_cluster(store, cluster_id)


def generate_nfs_bootstrap_script(cluster: dict) -> str:
    """NFS server first-boot script: stripe the data disks with mdadm,
    mkfs, export (shipyard_remotefs_bootstrap.sh setup_nfs :49
    analog, re-written for GCE device naming)."""
    export = cluster.get("export_path", "/export/shipyard")
    disks = int(cluster.get("disk_count", 2))
    dev_list = " ".join(
        f"/dev/disk/by-id/google-data{i}" for i in range(disks))
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu remotefs NFS bootstrap
if [ ! -e /dev/md0 ]; then
  mdadm --create /dev/md0 --level=0 --raid-devices={disks} {dev_list}
  mkfs.ext4 -F /dev/md0
fi
mkdir -p {export}
grep -q '/dev/md0' /etc/fstab || \\
  echo '/dev/md0 {export} ext4 defaults,noatime 0 0' >> /etc/fstab
mountpoint -q {export} || mount {export}
apt-get update && apt-get install -y nfs-kernel-server
grep -q '{export}' /etc/exports || \\
  echo '{export} *(rw,sync,no_subtree_check,no_root_squash)' \\
    >> /etc/exports
exportfs -ra
systemctl enable --now nfs-kernel-server
"""


def create_storage_cluster_mount_args(
        store: StateStore, cluster_id: str,
        mount_point: str = "/mnt/shipyard") -> list[str]:
    """fstab mount lines for compute-pool nodes
    (create_storage_cluster_mount_args remotefs.py:56 analog)."""
    cluster = get_storage_cluster(store, cluster_id)
    nodes = list(store.query_entities(_NODES_TABLE,
                                      partition_key=cluster_id))
    if not nodes:
        raise ValueError(
            f"storage cluster {cluster_id} has no provisioned nodes")
    server_ip = nodes[0].get("internal_ip")
    export = cluster.get("export_path", "/export/shipyard")
    if cluster.get("fs_type") == "nfs":
        return [f"{server_ip}:{export} {mount_point} nfs4 "
                f"defaults,_netdev,noatime,hard,proto=tcp 0 0"]
    raise ValueError(
        f"unsupported fs_type {cluster.get('fs_type')!r} "
        f"(gcsfuse mounts are configured via pool shared volumes)")


def gcsfuse_mount_args(bucket: str,
                       mount_point: str = "/mnt/gcs") -> list[str]:
    """GCS-FUSE shared volume mount (the serverless GlusterFS
    replacement for TPU pods)."""
    return [f"{bucket} {mount_point} gcsfuse "
            f"rw,_netdev,allow_other,implicit_dirs 0 0"]


def gcs_bucket_mount_commands(fs_config: dict, name: str) -> list[str]:
    """Render the nodeprep mount command for a gcs_buckets entry in
    fs.yaml (the RemoteFS-GCSFuse+Pool recipe's `fs bucket mount-args`
    surface): mkdir + gcsfuse with the configured options."""
    buckets = (fs_config.get("remote_fs") or {}).get(
        "gcs_buckets") or {}
    if name not in buckets:
        raise KeyError(
            f"gcs bucket {name!r} not in fs.yaml (have: "
            f"{sorted(buckets)})")
    import shlex

    entry = buckets[name] or {}
    # Values come from user-authored fs.yaml — quote everything that
    # reaches the shell so spaces/metacharacters cannot break or
    # inject into the nodeprep script.
    bucket = shlex.quote(str(entry.get("bucket") or name))
    mount_point = shlex.quote(
        str(entry.get("mount_point", f"/mnt/{name}")))
    opts = []
    for opt in entry.get("mount_options") or []:
        # Flag-style options (implicit-dirs) pass as --flags;
        # key=value pairs ride -o.
        if "=" in str(opt):
            opts.append(f"-o {shlex.quote(str(opt))}")
        else:
            opts.append(f"--{shlex.quote(str(opt))}")
    opt_str = (" ".join(opts) + " ") if opts else ""
    return [
        f"mkdir -p {mount_point} && "
        f"gcsfuse {opt_str}{bucket} {mount_point}",
    ]


def _vm_name(cluster_id: str) -> str:
    return f"shipyard-fs-{cluster_id}"


def _vm_manager(project: str, zone: Optional[str],
                network: Optional[str], vms=None):
    if vms is not None:
        return vms
    from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
    return GceVmManager(project, zone=zone, network=network)


def provision_nfs_server(store: StateStore, cluster_id: str,
                         project: str, zone: Optional[str] = None,
                         network: Optional[str] = None,
                         vms=None) -> None:
    """Create the NFS server VM + striped data disks
    (create_storage_cluster :623 + resource.py:680 analog). ``vms``
    injects a GceVmManager (tests pass a fake runner)."""
    vms = _vm_manager(project, zone, network, vms)
    cluster = get_storage_cluster(store, cluster_id)
    name = _vm_name(cluster_id)
    disks = []
    for i in range(int(cluster["disk_count"])):
        vms.create_disk(f"{name}-data{i}",
                        int(cluster["disk_size_gb"]),
                        cluster["disk_type"])
        disks.append((f"{name}-data{i}", f"data{i}"))
    ip = vms.create_vm(name, cluster["vm_size"],
                       startup_script=generate_nfs_bootstrap_script(
                           cluster),
                       disks=disks)
    store.upsert_entity(_NODES_TABLE, cluster_id, name, {
        "internal_ip": ip, "state": "running"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "provisioned"})


def suspend_storage_cluster(store: StateStore, cluster_id: str,
                            project: str, zone: Optional[str] = None,
                            vms=None) -> None:
    """Stop the server VM, keeping disks (remotefs.py:1680
    suspend_storage_cluster analog)."""
    vms = _vm_manager(project, zone, None, vms)
    get_storage_cluster(store, cluster_id)
    name = _vm_name(cluster_id)
    vms.stop_vm(name)
    store.upsert_entity(_NODES_TABLE, cluster_id, name,
                        {"state": "suspended"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "suspended"})


def start_storage_cluster(store: StateStore, cluster_id: str,
                          project: str, zone: Optional[str] = None,
                          vms=None) -> None:
    """Restart a suspended server VM (remotefs.py start analog)."""
    vms = _vm_manager(project, zone, None, vms)
    get_storage_cluster(store, cluster_id)
    name = _vm_name(cluster_id)
    vms.start_vm(name)
    store.upsert_entity(_NODES_TABLE, cluster_id, name, {
        "internal_ip": vms.internal_ip(name), "state": "running"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "provisioned"})


def storage_cluster_status(store: StateStore, cluster_id: str,
                           project: Optional[str] = None,
                           zone: Optional[str] = None,
                           vms=None) -> dict:
    """Cluster record + live VM status when reachable
    (remotefs.py:1929 stat analog)."""
    cluster = get_storage_cluster(store, cluster_id)
    nodes = list(store.query_entities(_NODES_TABLE,
                                     partition_key=cluster_id))
    status = {"cluster": cluster, "nodes": nodes}
    if project or vms is not None:
        vms = _vm_manager(project, zone, None, vms)
        try:
            status["vm_status"] = vms.vm_status(_vm_name(cluster_id))
        except Exception as exc:  # noqa: BLE001 - live probe optional
            status["vm_status"] = f"unknown ({exc})"
    return status


def resize_storage_cluster(store: StateStore, cluster_id: str,
                           new_vm_size: str, project: str,
                           zone: Optional[str] = None,
                           vms=None) -> None:
    """Change the server's machine type: stop -> set-machine-type ->
    start (remotefs.py:852 resize analog; GCE requires a stopped VM)."""
    vms = _vm_manager(project, zone, None, vms)
    cluster = get_storage_cluster(store, cluster_id)
    name = _vm_name(cluster_id)
    vms.stop_vm(name)
    vms.set_machine_type(name, new_vm_size)
    vms.start_vm(name)
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"vm_size": new_vm_size},
                       if_match=cluster["_etag"])
    store.upsert_entity(_NODES_TABLE, cluster_id, name, {
        "internal_ip": vms.internal_ip(name), "state": "running"})


def expand_storage_cluster_live(store: StateStore, cluster_id: str,
                                additional_disks: int, project: str,
                                zone: Optional[str] = None,
                                vms=None) -> str:
    """Attach new data disks to the live server and return the
    on-server grow script (remotefs.py:1171 expand + bootstrap's
    mdadm --add/--grow rebalance analog)."""
    vms = _vm_manager(project, zone, None, vms)
    cluster = get_storage_cluster(store, cluster_id)
    name = _vm_name(cluster_id)
    start = int(cluster["disk_count"])
    new_devices = []
    for i in range(start, start + additional_disks):
        vms.create_disk(f"{name}-data{i}",
                        int(cluster["disk_size_gb"]),
                        cluster["disk_type"])
        vms.attach_disk(name, f"{name}-data{i}", f"data{i}")
        new_devices.append(f"/dev/disk/by-id/google-data{i}")
    expand_storage_cluster(store, cluster_id, additional_disks)
    total = start + additional_disks
    devs = " ".join(new_devices)
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu remotefs expand: grow the RAID-0 stripe in place.
# RAID-0 cannot take --add'ed spares; growing it is the one-shot
# --grow --raid-devices=N --add form (mdadm reshapes via an implicit
# raid4 intermediate, then back to raid0).
mdadm --grow /dev/md0 --raid-devices={total} --add {devs}
resize2fs /dev/md0
"""


def register_server_node(store: StateStore, cluster_id: str,
                         node_name: str, internal_ip: str) -> None:
    """Record a server node (used by tests and external provisioning)."""
    store.upsert_entity(_NODES_TABLE, cluster_id, node_name, {
        "internal_ip": internal_ip, "state": "running"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "provisioned"})

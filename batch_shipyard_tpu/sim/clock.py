"""Virtual clock + event heap: the simulator's only notion of time.

THE one module in ``batch_shipyard_tpu/sim/`` allowed to even import
wall-clock sources (it doesn't need to: virtual time starts at 0.0
and advances only by popping the heap). Everything else in the
package is banned from ``time.time()``/``time.monotonic()``/
``datetime.now()`` by the ``sim-wall-clock`` analyzer rule — one
stray wall-clock read makes reports differ across runs and kills the
byte-identical determinism contract (tests/test_fleet_sim.py).
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Optional


class VirtualClock:
    """Monotonic virtual time; advances only via the event heap."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    @property
    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(
                f"virtual time went backwards: {t} < {self._now}")
        self._now = t


class EventHeap:
    """Deterministic priority queue of (time, seq, fn, payload).

    The monotonically increasing ``seq`` breaks same-time ties by
    schedule order — never by hash/dict order — so two runs with the
    same seed pop events identically."""

    def __init__(self, clock: VirtualClock) -> None:
        self._clock = clock
        self._heap: list = []
        self._seq = 0

    def __len__(self) -> int:
        return len(self._heap)

    def schedule(self, at: float, fn: Callable,
                 payload: Any = None) -> None:
        if at < self._clock.now:
            at = self._clock.now
        self._seq += 1
        heapq.heappush(self._heap, (at, self._seq, fn, payload))

    def schedule_in(self, delay: float, fn: Callable,
                    payload: Any = None) -> None:
        self.schedule(self._clock.now + max(0.0, delay), fn, payload)

    def pop(self) -> Optional[tuple]:
        """Advance the clock to the next event and return
        (fn, payload); None when drained."""
        if not self._heap:
            return None
        at, _seq, fn, payload = heapq.heappop(self._heap)
        self._clock.advance_to(at)
        return fn, payload

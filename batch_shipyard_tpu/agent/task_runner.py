"""Task execution on a node: env contract + runtime command synthesis.

Reference analog: scripts/shipyard_task_runner.sh +
shipyard_docker_exec_task_runner.sh (the SHIPYARD_RUNTIME env contract)
and the docker/singularity exec wiring in _construct_task
(convoy/batch.py:4640-4700). Re-designed in Python because our node
agent is Python and because TPU tasks need structured env synthesis
(JAX distributed vars) rather than string-templated bash.

Env contract exposed to every task (the $AZ_BATCH_* analog):

  SHIPYARD_POOL_ID / SHIPYARD_JOB_ID / SHIPYARD_TASK_ID
  SHIPYARD_NODE_ID / SHIPYARD_NODE_INDEX
  SHIPYARD_TASK_DIR        working directory for the task
  SHIPYARD_TASK_SLOT       slot index on this node
  SHIPYARD_HOST_LIST       comma-separated worker hostnames (gang tasks;
                           $AZ_BATCH_HOST_LIST analog, batch.py:4378)
  SHIPYARD_TASK_INSTANCES  gang size (1 for regular tasks)
  SHIPYARD_TASK_INSTANCE   this instance's index
  SHIPYARD_JOB_SHARED_DIR  node-local directory shared by every task
                           of the job ($AZ_BATCH_JOB_SHARED_DIR
                           analog; set by the node agent)
  SHIPYARD_JOB_SCRATCH     auto_scratch mount for the job (node-local
                           or the gang-shared NFS namespace; only set
                           when the job opts in)
  SHIPYARD_GOODPUT_FILE    JSONL sink for program-phase goodput events
                           (goodput/events.py record/phase); the agent
                           ingests it into TABLE_GOODPUT post-task
  SHIPYARD_PROGRESS_FILE   liveness file for the wedge watchdog
                           (agent/progress.py): instrumented workloads
                           beat it every step; tasks declaring
                           progress_deadline_seconds are killed when
                           it goes stale (hang -> bounded retry)
  SHIPYARD_TRACE_ID        distributed-trace context of this task
  SHIPYARD_TRACE_SPAN_ID   (trace/context.py): program spans recorded
  SHIPYARD_TRACE_FILE      in-process parent under the task's run
                           span; the JSONL span sink is ingested by
                           the agent post-task
  SHIPYARD_PROFILE_REQUEST_FILE  on-demand profiling (trace/
  SHIPYARD_PROFILE_DIR     profiling.py): the train harness watches
                           the request file and writes jax.profiler
                           captures into the dir, uploaded post-task
plus, for gang tasks with jax_distributed enabled, the launcher env from
jobs/launcher.py (JAX_COORDINATOR_ADDRESS etc.).
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import signal
import subprocess
import time
from typing import Optional

from batch_shipyard_tpu.agent import progress
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


@dataclasses.dataclass
class TaskExecution:
    """Everything needed to run one task instance on a node."""

    pool_id: str
    job_id: str
    task_id: str
    node_id: str
    node_index: int
    command: str
    runtime: str = "none"  # none | docker | singularity
    # Docker runtime for the container: runc (default) or
    # kata_containers -> `docker run --runtime kata-runtime`
    # (VM-isolated containers; reference shipyard_nodeprep.sh:1105
    # install + :1133 default-runtime wiring).
    container_runtime: str = "runc"
    image: Optional[str] = None
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    task_dir: str = "."
    slot: int = 0
    instances: int = 1
    instance: int = 0
    host_list: tuple[str, ...] = ()
    max_wall_time_seconds: Optional[float] = None
    # Wedge watchdog: kill the task when its progress file goes stale
    # past this deadline (None = watchdog disabled for this task).
    progress_deadline_seconds: Optional[float] = None
    remove_container_after_exit: bool = True
    shm_size: Optional[str] = None
    additional_docker_run_options: tuple[str, ...] = ()
    additional_singularity_options: tuple[str, ...] = ()
    docker_exec_in: Optional[str] = None  # exec into a running container
    interactive: bool = False
    # Crash-restart adoption contract (agent/node_agent.py slot
    # ledger): when set, the task's exit code is persisted to
    # EXIT_CODE_FILENAME in task_dir — by a shell trailer inside the
    # task's own session for runtime "none" (survives the agent
    # process dying) AND by run_task after reaping (covers kill
    # paths). A restarted agent adopting the still-running process
    # reads the file to classify the exit it never got to wait() on.
    record_exit_code: bool = False


@dataclasses.dataclass
class TaskResult:
    exit_code: int
    stdout_path: str
    stderr_path: str
    started_at: str
    completed_at: str
    wall_seconds: float
    timed_out: bool = False
    # True when the wedge watchdog killed the task for missing its
    # progress deadline (alive but stalled — the TPU-wedge shape).
    wedged: bool = False


def build_task_env(execution: TaskExecution,
                   base_env: Optional[dict[str, str]] = None,
                   ) -> dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env.update(execution.env)
    env.update({
        "SHIPYARD_POOL_ID": execution.pool_id,
        "SHIPYARD_JOB_ID": execution.job_id,
        "SHIPYARD_TASK_ID": execution.task_id,
        "SHIPYARD_NODE_ID": execution.node_id,
        "SHIPYARD_NODE_INDEX": str(execution.node_index),
        "SHIPYARD_TASK_DIR": execution.task_dir,
        "SHIPYARD_TASK_SLOT": str(execution.slot),
        "SHIPYARD_TASK_INSTANCES": str(execution.instances),
        "SHIPYARD_TASK_INSTANCE": str(execution.instance),
    })
    if execution.host_list:
        env["SHIPYARD_HOST_LIST"] = ",".join(execution.host_list)
    return env


def container_name(execution: "TaskExecution") -> Optional[str]:
    """The fixed docker ``--name`` for this execution, or None for
    non-docker runtimes and exec-in tasks (which attach to a
    container somebody else owns)."""
    if execution.runtime == "docker" and not execution.docker_exec_in:
        return (f"shipyard-{execution.job_id}-{execution.task_id}"
                f"-i{execution.instance}")
    return None


# ------------------------- in-process runtime --------------------------
#
# runtime: "inproc" — the task runs as a FUNCTION CALL inside the
# agent's worker thread: no fork, no /bin/bash, no task-dir creation,
# no stdout files. This is the 10^5-task scheduler-proof mode: at that
# scale per-task subprocess cost (fork+exec+pipe teardown, ~10ms each)
# dominates the scheduler benchmark and the measurement stops being
# about scheduling. Everything ABOVE the runner (claims, state
# transitions, goodput/trace emission, queue drain) runs the real
# path. The command string's first token selects a registered
# callable; unknown commands exit 127 like a shell would.

def _inproc_noop(execution: "TaskExecution") -> int:
    return 0


def _inproc_fail(execution: "TaskExecution") -> int:
    return 1


def _inproc_preempt_exit(execution: "TaskExecution") -> int:
    """Exit preempted immediately (test hook for the requeue path)."""
    from batch_shipyard_tpu.agent import preemption
    return preemption.EXIT_PREEMPTED


INPROC_COMMANDS = {
    "noop": _inproc_noop,
    "fail": _inproc_fail,
    "preempt-exit": _inproc_preempt_exit,
}


def _run_inproc(execution: TaskExecution) -> TaskResult:
    started_at = util.datetime_utcnow_iso()
    start = time.monotonic()
    name = (execution.command or "noop").split(None, 1)[0]
    fn = INPROC_COMMANDS.get(name)
    if fn is None:
        exit_code = 127
    else:
        try:
            exit_code = int(fn(execution) or 0)
        except Exception:  # noqa: BLE001 - a task bug is exit 1,
            # never an agent-thread crash
            logger.exception("inproc task %s failed", name)
            exit_code = 1
    return TaskResult(
        exit_code=exit_code, stdout_path="", stderr_path="",
        started_at=started_at,
        completed_at=util.datetime_utcnow_iso(),
        wall_seconds=time.monotonic() - start)


# Where the exit-code sentinel lands, relative to task_dir (the
# command runs with cwd=task_dir, so the shell trailer needs no
# absolute path and no env remap).
EXIT_CODE_FILENAME = ".shipyard_exitcode"


def _exit_recorded_command(command: str) -> str:
    """Wrap a runtime-"none" command so its exit code lands in
    EXIT_CODE_FILENAME from INSIDE the task's own session: the
    write happens even when the spawning agent process is long dead
    (tasks run start_new_session=True and outlive an agent crash —
    the adoption scenario). tmp+mv so a reader never sees a torn
    write; the original exit code is preserved."""
    return (f"( {command}\n); __shipyard_ec=$?; "
            f"printf '%s' \"$__shipyard_ec\" "
            f"> {EXIT_CODE_FILENAME}.tmp && "
            f"mv {EXIT_CODE_FILENAME}.tmp {EXIT_CODE_FILENAME}; "
            f"exit $__shipyard_ec")


def synthesize_command(execution: TaskExecution) -> list[str]:
    """Build the argv for the task's runtime.

    docker/singularity lines mirror the capability surface of the
    reference's run-option synthesis (batch.py:4640-4700) with TPU
    device passthrough in place of --gpus.
    """
    if execution.runtime == "none":
        command = execution.command
        if execution.record_exit_code:
            command = _exit_recorded_command(command)
        return ["/bin/bash", "-c", command]
    if execution.runtime == "docker":
        if execution.docker_exec_in:
            argv = ["docker", "exec", execution.docker_exec_in,
                    "/bin/bash", "-c", execution.command]
            return argv
        argv = ["docker", "run"]
        if execution.container_runtime == "kata_containers":
            argv += ["--runtime", "kata-runtime"]
        if execution.remove_container_after_exit:
            argv.append("--rm")
        argv += ["--name", container_name(execution)]
        if execution.interactive:
            argv.append("-it")
        # TPU device passthrough (the nvidia-runtime analog).
        if os.path.exists("/dev/accel0") or os.environ.get(
                "SHIPYARD_FORCE_TPU_PASSTHROUGH"):
            argv += ["--privileged", "--device", "/dev/accel0",
                     "--net", "host"]
        if execution.shm_size:
            argv += ["--shm-size", execution.shm_size]
        argv += ["-w", "/shipyard/task", "-v",
                 f"{execution.task_dir}:/shipyard/task"]
        for key in sorted(execution.env):
            argv += ["-e", key]
        for var in ("SHIPYARD_POOL_ID", "SHIPYARD_JOB_ID",
                    "SHIPYARD_TASK_ID", "SHIPYARD_NODE_ID",
                    "SHIPYARD_NODE_INDEX", "SHIPYARD_TASK_INSTANCES",
                    "SHIPYARD_TASK_INSTANCE", "SHIPYARD_HOST_LIST",
                    "SHIPYARD_TASK_SLOT"):
            argv += ["-e", var]
        # SHIPYARD_TASK_DIR names the HOST path; inside the container
        # the task dir is the /shipyard/task mount, so forward the
        # remapped value rather than the bare passthrough.
        argv += ["-e", "SHIPYARD_TASK_DIR=/shipyard/task"]
        goodput_file = execution.env.get("SHIPYARD_GOODPUT_FILE")
        if goodput_file:
            # The host task_dir is mounted at /shipyard/task: remap
            # the recorder path onto the mount so the agent finds the
            # file on the host side after exit. A sink outside this
            # execution's task_dir (e.g. a gang coordination step
            # whose task_dir is a subdir) is unreachable through the
            # mount — leave the env alone; the recorder's writes are
            # simply lost with the container, never an error.
            host_dir = os.path.abspath(execution.task_dir)
            host_file = os.path.abspath(goodput_file)
            if host_file.startswith(host_dir + os.sep):
                rel = os.path.relpath(host_file, host_dir)
                argv += ["-e",
                         f"SHIPYARD_GOODPUT_FILE=/shipyard/task/{rel}"]
        progress_file = execution.env.get(progress.PROGRESS_FILE_ENV)
        if progress_file:
            # Same mount remap as the goodput sink: beats written
            # inside the container must land where the host-side
            # watchdog stats them.
            host_dir = os.path.abspath(execution.task_dir)
            host_file = os.path.abspath(progress_file)
            if host_file.startswith(host_dir + os.sep):
                rel = os.path.relpath(host_file, host_dir)
                argv += ["-e",
                         f"{progress.PROGRESS_FILE_ENV}="
                         f"/shipyard/task/{rel}"]
        # Trace-span sink + profiling request/artifact paths: same
        # mount remap — the agent reads all three host-side after
        # exit (SHIPYARD_TRACE_ID/_SPAN_ID are plain values and pass
        # through the generic -e loop above untouched).
        for var in ("SHIPYARD_TRACE_FILE",
                    "SHIPYARD_PROFILE_REQUEST_FILE",
                    "SHIPYARD_PROFILE_DIR",
                    "SHIPYARD_PREEMPT_REQUEST_FILE"):
            host_path = execution.env.get(var)
            if not host_path:
                continue
            host_dir = os.path.abspath(execution.task_dir)
            host_abs = os.path.abspath(host_path)
            if host_abs.startswith(host_dir + os.sep):
                rel = os.path.relpath(host_abs, host_dir)
                argv += ["-e", f"{var}=/shipyard/task/{rel}"]
        cache_dir = execution.env.get("SHIPYARD_COMPILE_CACHE_DIR")
        if cache_dir:
            # The node's persistent compile cache lives OUTSIDE the
            # task dir (it is shared by every task on the node): give
            # it its own mount and point the env at the mount, so the
            # containerized workload's warm entries land where the
            # agent's seed/export hooks find them.
            argv += ["-v",
                     f"{os.path.abspath(cache_dir)}:"
                     f"/shipyard/compilecache",
                     "-e", "SHIPYARD_COMPILE_CACHE_DIR="
                           "/shipyard/compilecache"]
        argv += list(execution.additional_docker_run_options)
        argv += [execution.image or "",
                 "/bin/bash", "-c", execution.command]
        return argv
    if execution.runtime == "singularity":
        argv = ["singularity", "exec"]
        if os.path.exists("/dev/accel0"):
            argv += ["--bind", "/dev:/dev", "--writable-tmpfs"]
        argv += list(execution.additional_singularity_options)
        argv += [execution.image or "",
                 "/bin/bash", "-c", execution.command]
        return argv
    raise ValueError(f"unknown runtime {execution.runtime!r}")


def run_task(execution: TaskExecution,
             base_env: Optional[dict[str, str]] = None,
             on_start=None) -> TaskResult:
    """Execute the task, streaming stdout/stderr to files in task_dir.

    Enforces max_wall_time by process-group kill (the agent-side analog
    of Azure Batch maxWallClockTime task constraints). ``on_start`` is
    called with the Popen handle once the process exists (used by the
    agent to support task termination).
    """
    if execution.runtime == "inproc":
        return _run_inproc(execution)
    os.makedirs(execution.task_dir, exist_ok=True)
    if execution.record_exit_code:
        # A stale sentinel from a previous attempt in the same task
        # dir must never classify THIS attempt's exit.
        for stale in (EXIT_CODE_FILENAME, EXIT_CODE_FILENAME + ".tmp"):
            try:
                os.remove(os.path.join(execution.task_dir, stale))
            except OSError:
                pass
    stdout_path = os.path.join(execution.task_dir, "stdout.txt")
    stderr_path = os.path.join(execution.task_dir, "stderr.txt")
    env = build_task_env(execution, base_env)
    argv = synthesize_command(execution)
    started_at = util.datetime_utcnow_iso()
    start = time.monotonic()
    timed_out = False
    wedged = False
    progress_file = execution.env.get(progress.PROGRESS_FILE_ENV)
    watchdog = execution.progress_deadline_seconds
    if progress_file:
        # Spawn counts as the first beat: the watchdog clock starts
        # now, and un-instrumented-but-opted-in tasks get the full
        # deadline before their first (never-coming) beat is due.
        progress.seed(progress_file)
    with open(stdout_path, "wb") as out, open(stderr_path, "wb") as err:
        proc = subprocess.Popen(
            argv, stdout=out, stderr=err, env=env, cwd=execution.task_dir,
            start_new_session=True)
        if on_start is not None:
            on_start(proc)
        policing = watchdog is not None and progress_file
        while True:
            if policing:
                timeout = _WATCHDOG_POLL_SECONDS
            elif execution.max_wall_time_seconds is not None:
                # Wall limit only: sleep straight to the deadline —
                # no 5 Hz wakeups over a multi-hour task lifetime.
                timeout = max(0.1, execution.max_wall_time_seconds
                              - (time.monotonic() - start))
            else:
                # Nothing to police: one blocking wait.
                timeout = None
            try:
                exit_code = proc.wait(timeout=timeout)
                break
            except subprocess.TimeoutExpired:
                pass
            elapsed = time.monotonic() - start
            if execution.max_wall_time_seconds is not None and \
                    elapsed > execution.max_wall_time_seconds:
                timed_out = True
                logger.warning(
                    "task %s/%s/%s exceeded wall time %.1fs; killing",
                    execution.pool_id, execution.job_id,
                    execution.task_id,
                    execution.max_wall_time_seconds)
                exit_code = _kill_task(
                    proc, grace_seconds=10.0,
                    container=container_name(execution))
                break
            if watchdog is not None and progress_file:
                beat = progress.last_beat(progress_file)
                stale = (elapsed if beat is None
                         else time.time() - beat)
                if stale > watchdog:
                    # Wedged: alive but no progress. SIGKILL straight
                    # away — the motivating hangs (TPU_WEDGE_REPORT.md)
                    # sit inside the runtime and never honor SIGTERM.
                    wedged = True
                    logger.warning(
                        "task %s/%s/%s made no progress for %.1fs "
                        "(deadline %.1fs); killing as wedged",
                        execution.pool_id, execution.job_id,
                        execution.task_id, stale, watchdog)
                    exit_code = _kill_task(
                        proc, grace_seconds=0.0,
                        container=container_name(execution))
                    break
    wall = time.monotonic() - start
    if execution.record_exit_code:
        # Belt to the shell trailer's suspenders: kill paths (wedge /
        # wall-time SIGKILL) never run the trailer, so the reaping
        # process records the code it saw. tmp+rename like the
        # trailer; best-effort — the adoption reader treats a missing
        # sentinel as an unknown (failed) exit.
        sentinel = os.path.join(execution.task_dir,
                                EXIT_CODE_FILENAME)
        try:
            util.atomic_write(sentinel, str(exit_code).encode())
        except OSError:
            logger.debug("exit-code sentinel write failed",
                         exc_info=True)
    return TaskResult(
        exit_code=exit_code, stdout_path=stdout_path,
        stderr_path=stderr_path, started_at=started_at,
        completed_at=util.datetime_utcnow_iso(), wall_seconds=wall,
        timed_out=timed_out, wedged=wedged)


# Watchdog poll granularity: how often a running task's wall-time and
# progress deadlines are re-checked. Small enough that tests with
# ~second deadlines stay sharp; large enough to cost nothing.
_WATCHDOG_POLL_SECONDS = 0.2


def _kill_task(proc, grace_seconds: float = 10.0,
               container: Optional[str] = None) -> int:
    """Kill a task's whole process group: SIGTERM with a grace window,
    then SIGKILL (grace_seconds=0 goes straight to SIGKILL — the
    wedge path, where SIGTERM provably never lands).

    For docker tasks the process-group escalation only reaches the
    docker CLIENT: SIGKILL is never proxied, so the container (and
    the accelerator it holds) would live on, and its fixed --name
    would break every retry landing on this node. Before the hard
    kill, force-remove the container so the workload actually dies
    and the name is freed. (SIGTERM in the grace window IS proxied
    by the client, so graceful shutdown still works.)"""
    if grace_seconds > 0:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            return proc.wait(timeout=grace_seconds)
        except subprocess.TimeoutExpired:
            pass
    if container is not None:
        _force_remove_container(container)
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except ProcessLookupError:
        pass
    return proc.wait()


def _force_remove_container(name: str) -> None:
    try:
        subprocess.run(["docker", "rm", "-f", name],
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, timeout=30)
    except Exception:  # noqa: BLE001 - kill escalation proceeds anyway
        logger.warning("docker rm -f %s failed", name, exc_info=True)


def format_command_line(argv: list[str]) -> str:
    return " ".join(shlex.quote(a) for a in argv)

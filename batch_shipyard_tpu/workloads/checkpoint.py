"""Training checkpoint/resume via Orbax.

Reference context (SURVEY.md section 5.4): the reference has no
application checkpointing (it is an orchestrator); for the TPU build,
app-level checkpointing is a workload concern — this module gives the
recipe payloads a save/restore surface over Orbax so preempted or
migrated jobs resume instead of restarting. Orchestrator-level
suspend/resume and job migration live in pool/jobs managers.

Checkpoints go to a local path or, in a pool, typically the job's
shared directory (SHIPYARD_JOB_SHARED_DIR) or a gcsfuse mount so every
worker sees them.

Atomic commit protocol: a save writes into a hidden staging directory
(``.tmp_step_NNNNNNNN``), stamps a COMMITTED marker, then renames into
place — so a crash mid-save can never leave a torn ``step_NNNNNNNN``
that ``latest_step``/``restore`` would pick up and resume a corrupt
state from. ``latest_step`` only considers dirs carrying the marker,
which also skips torn dirs written by pre-marker versions. This is
what makes the goodput "lost-step rework" number honest: resume
always lands on the last DURABLE step, and the replayed step window
after a preemption is exactly the badput the accounting charges.

Save/restore durations are recorded as goodput program-phase events
(checkpoint-overhead badput) through the process-local recorder when
the task env carries SHIPYARD_GOODPUT_FILE.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

COMMIT_MARKER = "COMMITTED"


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        f"step_{step:08d}")


def _staging_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        f".tmp_step_{step:08d}")


def _marker_path(checkpoint_dir: str, step: int) -> str:
    # Sibling file, not inside the step dir: Orbax owns the dir's
    # contents and must never see a foreign entry on restore.
    return _step_path(checkpoint_dir, step) + "." + COMMIT_MARKER


def is_committed(checkpoint_dir: str, step: int) -> bool:
    return os.path.exists(_marker_path(checkpoint_dir, step))


def save(checkpoint_dir: str, step: int, params: Any,
         opt_state: Any) -> str:
    """Write checkpoint step N atomically; returns its path."""
    import jax
    path = _step_path(checkpoint_dir, step)
    staging = _staging_path(checkpoint_dir, step)
    state = {"params": params, "opt_state": opt_state,
             "step": step}
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_SAVE, step=step):
        if jax.process_index() == 0:
            os.makedirs(checkpoint_dir, exist_ok=True)
            # A stale staging dir is a previous torn save: discard.
            shutil.rmtree(staging, ignore_errors=True)
        _checkpointer().save(staging, state, force=True)
        if jax.process_index() == 0:
            # Commit order: replace the step dir, THEN stamp the
            # marker (atomically, tmp + rename) — a crash at any
            # point leaves either a previously committed step or an
            # unmarked (ignored) dir, never a torn pickup. A marker
            # orphaned by a crash mid-overwrite is harmless:
            # latest_step only considers EXISTING step dirs.
            marker = _marker_path(checkpoint_dir, step)
            shutil.rmtree(path, ignore_errors=True)
            os.replace(staging, path)
            marker_tmp = marker + ".tmp"
            with open(marker_tmp, "w", encoding="utf-8") as fh:
                fh.write(util.datetime_utcnow_iso())
            os.replace(marker_tmp, marker)
    logger.info("checkpoint saved: %s", path)
    return path


def latest_step(checkpoint_dir: str) -> Optional[int]:
    """Highest COMMITTED step, skipping torn/uncommitted dirs.

    Legacy compatibility: a directory written ENTIRELY by pre-marker
    versions (no .COMMITTED files at all) keeps the old accept-all
    behavior — upgrading must not silently discard a fleet's existing
    resume points. As soon as one marker exists, enforcement is
    strict: unmarked step dirs are torn saves."""
    if not os.path.isdir(checkpoint_dir):
        return None
    entries = os.listdir(checkpoint_dir)
    any_marker = any(name.endswith("." + COMMIT_MARKER)
                     for name in entries)
    steps = []
    for name in entries:
        if name.startswith("step_") and \
                not name.endswith("." + COMMIT_MARKER):
            try:
                step = int(name.split("_", 1)[1])
            except ValueError:
                continue
            if any_marker and not is_committed(checkpoint_dir, step):
                logger.warning(
                    "skipping uncommitted checkpoint %s (torn save)",
                    os.path.join(checkpoint_dir, name))
                continue
            steps.append(step)
    return max(steps) if steps else None


def restore_params(checkpoint_dir: str) -> Optional[tuple]:
    """Restore only the params of the latest checkpoint (serving:
    the optimizer state is irrelevant and its template unavailable).
    Returns (params, step) or None. Arrays land unsharded on the
    default device — single-host serving replicas."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_RESTORE, step=step):
        restored = _checkpointer().restore(path)
    logger.info("checkpoint params restored: %s", path)
    return restored["params"], restored.get("step", step)


def restore(checkpoint_dir: str, params_template: Any,
            opt_state_template: Any) -> Optional[tuple]:
    """Restore the latest committed checkpoint matching the given
    pytree structure (shardings preserved from the templates); returns
    (params, opt_state, step) or None when no checkpoint exists."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": step}
    import orbax.checkpoint as ocp
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_RESTORE, step=step):
        restored = _checkpointer().restore(
            path, item=template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                template))
    logger.info("checkpoint restored: %s", path)
    return restored["params"], restored["opt_state"], restored["step"]

"""Ring-collective kernel correctness (ops/ring_collectives.py).

Pallas interpret mode aborts inside shard_map on CPU (see
ring_attention.py), so — exactly like the flash-ring tests — the
kernels are exercised single-device/virtual-shard style: the virtual
ring kernels run the SAME double-buffered slot schedule the
remote-DMA kernels use (shared via ag_source_shard / rs_chunk_index)
with local async DMA copies standing in for the remote ones, and are
checked against the jax.lax collectives running over the virtual
8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from batch_shipyard_tpu.ops import ring_attention, ring_collectives as rc
from batch_shipyard_tpu.ops import kernel_select
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.utils.compat import shard_map


def _shards(ring, chunk, feat, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(ring, chunk, feat), jnp.float32)


# ---------------- schedule arithmetic ---------------------------------

def test_all_gather_schedule_covers_every_shard():
    """Over ring-1 steps plus the local shard, every device sees every
    source exactly once — the invariant the output copies rely on."""
    for ring in (2, 3, 4, 8):
        for me in range(ring):
            seen = {me} | {rc.ag_source_shard(me, t, ring)
                           for t in range(ring - 1)}
            assert seen == set(range(ring))


def test_reduce_scatter_schedule_lands_own_chunk():
    """The partial chain for chunk c starts at device c+1 and, after
    ring-1 forwarding hops, lands on device c fully reduced — the
    psum_scatter(tiled) layout."""
    for ring in (2, 3, 4, 8):
        for me in range(ring):
            # Chunk received at the last step is this device's own.
            assert rc.rs_chunk_index(me, ring - 2, ring) == me
            # Each step touches a distinct chunk.
            chunks = {rc.rs_chunk_index(me, t, ring)
                      for t in range(-1, ring - 1)}
            assert chunks == set(range(ring))


# ---------------- virtual kernels vs jax.lax references ---------------

@pytest.mark.parametrize("ring", [2, 4, 8])
def test_virtual_all_gather_matches_lax(ring):
    x = _shards(ring, 16, 128)
    got = rc.ring_all_gather_virtual(x, interpret=True)
    # jax.lax reference over the CPU mesh: gather the same shards.
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, sp=ring),
                              devices=jax.devices()[:8])
    ref = shard_map(
        lambda s: jax.lax.all_gather(s[0], "sp", tiled=True),
        mesh=mesh, in_specs=P("sp"), out_specs=P(None),
        check_vma=False)(x)
    assert got.shape == (ring, ring * 16, 128)
    for i in range(ring):
        np.testing.assert_allclose(np.asarray(got[i]),
                                   np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("ring", [2, 4, 8])
def test_virtual_reduce_scatter_matches_lax(ring):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(ring, ring * 16, 128), jnp.float32)
    got = rc.ring_reduce_scatter_virtual(x, interpret=True)
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, sp=ring),
                              devices=jax.devices()[:8])
    ref = shard_map(
        lambda s: jax.lax.psum_scatter(s[0], "sp", tiled=True),
        mesh=mesh, in_specs=P("sp", None), out_specs=P("sp"),
        check_vma=False)(x)
    got_flat = got.reshape(ring * 16, 128)
    np.testing.assert_allclose(np.asarray(got_flat), np.asarray(ref),
                               atol=1e-4, rtol=1e-5)
    rel = (np.linalg.norm(np.asarray(got_flat) - np.asarray(ref)) /
           np.linalg.norm(np.asarray(ref)))
    assert rel < 1e-6, rel


def test_virtual_kernels_reject_trivial_ring():
    with pytest.raises(ValueError):
        rc.ring_all_gather_virtual(_shards(1, 16, 128))
    with pytest.raises(ValueError):
        rc.ring_reduce_scatter_virtual(_shards(1, 16, 128))
    with pytest.raises(ValueError):
        # Row length must divide the ring.
        rc.ring_reduce_scatter_virtual(_shards(4, 18, 128))


def test_virtual_all_gather_non_contiguous_values():
    """Chunk identity, not just sums: each gathered position holds the
    exact source shard (catches slot-arithmetic off-by-ones that a
    symmetric random test could mask)."""
    ring, chunk, feat = 4, 8, 128
    x = jnp.stack([jnp.full((chunk, feat), float(i + 1))
                   for i in range(ring)])
    got = rc.ring_all_gather_virtual(x, interpret=True)
    for i in range(ring):
        for src in range(ring):
            block = np.asarray(
                got[i, src * chunk:(src + 1) * chunk])
            assert (block == src + 1).all(), (i, src)


# ---------------- pallas_dma tier resolution --------------------------

def test_resolve_ring_impl_accepts_pallas_dma(monkeypatch):
    monkeypatch.setenv("SHIPYARD_RING_IMPL", "pallas_dma")
    assert ring_attention.resolve_ring_impl("auto") == "pallas_dma"
    # Explicit impl still beats the env var.
    assert ring_attention.resolve_ring_impl("xla") == "xla"
    monkeypatch.setenv("SHIPYARD_RING_IMPL", "bogus")
    with pytest.raises(ValueError):
        ring_attention.resolve_ring_impl("auto")


def test_pallas_dma_auto_stays_off_on_cpu(tmp_path, monkeypatch):
    """Even a tpu-backed ring_collectives pass does not flip auto on
    a cpu backend — the gate is backend AND marker (kernel_select)."""
    import json
    marker = tmp_path / "KERNEL_VALIDATION.json"
    marker.write_text(json.dumps({
        "flash_ring": {"ok": True, "backend": "tpu"},
        "ring_collectives": {"ok": True, "backend": "tpu"}}))
    monkeypatch.setenv(kernel_select.MARKER_ENV, str(marker))
    assert kernel_select.kernel_validated("ring_collectives")
    assert ring_attention.resolve_ring_impl("auto") == "xla"


def test_pallas_dma_auto_needs_both_markers(tmp_path, monkeypatch):
    """On a TPU backend (simulated), auto climbs the tiers exactly as
    far as the markers allow: nothing -> xla, flash_ring -> flash,
    flash_ring + ring_collectives -> pallas_dma."""
    import json
    marker = tmp_path / "KERNEL_VALIDATION.json"
    monkeypatch.setenv(kernel_select.MARKER_ENV, str(marker))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    marker.write_text(json.dumps({}))
    assert ring_attention.resolve_ring_impl("auto") == "xla"
    marker.write_text(json.dumps({
        "flash_ring": {"ok": True, "backend": "tpu"}}))
    assert ring_attention.resolve_ring_impl("auto") == "flash"
    marker.write_text(json.dumps({
        "flash_ring": {"ok": True, "backend": "tpu"},
        "ring_collectives": {"ok": True, "backend": "tpu"}}))
    assert ring_attention.resolve_ring_impl("auto") == "pallas_dma"
    # A ring_collectives pass WITHOUT the flash one must not skip a
    # tier: the DMA path builds on the flash rotation kernels.
    marker.write_text(json.dumps({
        "ring_collectives": {"ok": True, "backend": "tpu"}}))
    assert ring_attention.resolve_ring_impl("auto") == "xla"

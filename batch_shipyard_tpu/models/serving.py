"""Continuous batching: a slot-based serving engine over the KV-cache
decode path.

ROADMAP item (the reference has no serving story): instead of
generating whole batches in lockstep (models/inference.generate —
every sequence must finish before any slot frees), the engine holds a
fixed pool of decode SLOTS sharing one batched KV cache. Requests
admit into free slots as they arrive (per-slot prefill via a batch-1
scatter into the big cache), every engine step decodes ONE token for
all active slots in a single jitted call, and finished slots free
immediately for the next request — the throughput property
continuous-batching servers (Orca/vLLM-class) are built around.

TPU-first mechanics: the per-slot cache index ([B] int32,
transformer._decode_attend) lets slots sit at different depths in one
[B, T, H, D] cache; per-slot RoPE positions ride the 2-D positions
path; everything is static-shape jitted — admit/emit bookkeeping is
host-side Python, compute is two compiled functions (prefill, step).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import time
import uuid
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import transformer as tfm


@functools.partial(jax.jit, static_argnames=("model", "sampling"))
def _decode_step(model, sampling, params, cache, tokens, positions,
                 active, key):
    """One token for every slot in one compiled call. MODULE-LEVEL
    with the model/sampling static so identical engines — fleet
    replicas sharing one param tree, or a test suite constructing
    many same-config engines — share ONE compilation instead of
    re-tracing per ContinuousBatcher instance."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens,
        positions=positions[:, None], mutable=["cache"])
    next_tok = inf._sample(logits[:, 0].astype(jnp.float32),
                           key, sampling)
    # Inactive slots DO write garbage into their cache rows,
    # and that is fine: a freed row is never read (the
    # per-slot mask excludes other rows) and _admit's prefill
    # rewrites the whole row + index before reuse — restoring
    # the full K/V trees here would double per-token HBM
    # traffic for no observable effect. Only the cheap token/
    # position bookkeeping needs masking.
    next_tok = jnp.where(active, next_tok, tokens[:, 0])
    positions = jnp.where(active, positions + 1, positions)
    return (mutated["cache"], next_tok[:, None], positions,
            next_tok)


@functools.partial(jax.jit, static_argnames=(
    "target_model", "draft_model", "gamma"))
def _speculative_step(target_model, draft_model, gamma, t_params,
                      d_params, t_cache, d_cache, tokens, positions,
                      active):
    """One ragged draft/verify round over the full slot batch.
    tokens [B, 1] is each slot's pending token y (sampled but not yet
    cached), positions [B] its absolute position — both caches hold
    every committed token EXCEPT y (the speculative_generate
    invariant, per slot).

    Draft: gamma+1 batched single-token steps propose d_1..d_gamma
    (the extra step only inserts d_gamma's K/V so the draft cache
    keeps pace on full acceptance). Verify: ONE batched target
    forward scores [y, d_1..d_gamma] through the multi-token
    cache-insert path (per-slot write indices + 2-D RoPE positions
    make the batch ragged-safe). Accept: each slot's longest
    validated prefix a_i, commit d_1..d_{a_i} plus the target token
    at a_i (correction or bonus), rewind both caches by gamma - a_i
    per slot — the paged target rewinds its per-slot length the same
    way. Inactive slots rewind the full gamma+1 so their indices
    stay put. Module-level jit (statics as above) so same-shape
    engines share the compilation."""
    d_embed = d_params["embed"]["embedding"]
    t_embed = t_params["embed"]["embedding"]

    def draft_step(carry, _):
        cache, tok, pos = carry
        hidden, mut = draft_model.apply(
            {"params": d_params, "cache": cache}, tok,
            return_hidden=True, positions=pos[:, None],
            mutable=["cache"])
        logits = jnp.dot(
            hidden[:, 0].astype(jnp.float32),
            d_embed.astype(jnp.float32).T)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (mut["cache"], nxt[:, None], pos + 1), nxt

    (d_cache, _, _), drafts = jax.lax.scan(
        draft_step, (d_cache, tokens, positions), None,
        length=gamma + 1)
    d_tok = jnp.moveaxis(drafts, 0, 1)[:, :gamma]        # [B, g]
    x_blk = jnp.concatenate([tokens, d_tok], axis=1)
    pos_blk = positions[:, None] + jnp.arange(
        gamma + 1, dtype=jnp.int32)[None, :]
    hidden, mut = target_model.apply(
        {"params": t_params, "cache": t_cache}, x_blk,
        return_hidden=True, positions=pos_blk,
        mutable=["cache"])
    t_cache = mut["cache"]
    logits = jnp.einsum(
        "bsd,vd->bsv", hidden.astype(jnp.float32),
        t_embed.astype(jnp.float32))
    t_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, g+1]
    match = (d_tok == t_tok[:, :gamma])
    a_slot = jnp.sum(jnp.cumprod(
        match.astype(jnp.int32), axis=1), axis=1)          # [B]
    a_slot = jnp.where(active, a_slot, 0)
    js = jnp.arange(gamma + 1, dtype=jnp.int32)
    d_pad = jnp.concatenate(
        [d_tok, jnp.zeros((d_tok.shape[0], 1), jnp.int32)], axis=1)
    block = jnp.where(js[None, :] < a_slot[:, None], d_pad,
                      t_tok)                               # [B, g+1]
    rewind = jnp.where(active, gamma - a_slot, gamma + 1)
    t_cache = inf._rewind_cache(t_cache, rewind)
    d_cache = inf._rewind_cache(d_cache, rewind)
    new_tok = jnp.take_along_axis(block, a_slot[:, None],
                                  axis=1)                  # [B, 1]
    new_tok = jnp.where(active[:, None], new_tok, tokens)
    new_pos = jnp.where(active, positions + a_slot + 1, positions)
    return t_cache, d_cache, new_tok, new_pos, block, a_slot


def _dense_prefill(model, prefill_chunk, params, prompt, prompt_len):
    """Batch-1 BATCHED prefill over the (bucket-padded) prompt
    [1, L]: the multi-token insert path of transformer._decode_attend
    writes all L cache rows and attends causally in MXU-batched
    passes — prefill wall-clock is one forward (or ceil(L/chunk)
    chunked forwards with prefill_chunk set, bounding the score
    tensor at O(chunk * max_decode_len)), not L sequential
    micro-steps. Compiles remain one per length bucket.

    prompt_len is DYNAMIC (a traced int32): rows written past
    prompt_len are garbage, but they are masked-on-read
    (key_pos <= idx) and each is overwritten by the decode step that
    first reaches its position, so only the length bookkeeping needs
    the true value. This is what makes L bucketable: one compile per
    BUCKET instead of one per distinct prompt length.

    The last-token logits come from the final hidden state at
    prompt_len-1 (return_hidden + a [d, vocab] matvec) so the full
    [L, vocab] fp32 logits tensor never materializes."""
    small = inf.init_cache(model, params, 1)
    total = prompt.shape[1]
    chunk = min(prefill_chunk or total, total)
    hiddens = []
    cache = small
    for off in range(0, total, chunk):
        seg = prompt[:, off:off + chunk]
        # Positions are GLOBAL offsets: RoPE for chunk c must match
        # the full-sequence pass exactly.
        h, mut = model.apply(
            {"params": params, "cache": cache}, seg,
            return_hidden=True,
            positions=jnp.arange(
                off, off + seg.shape[1], dtype=jnp.int32),
            mutable=["cache"])
        cache = mut["cache"]
        hiddens.append(h)
    hidden = (hiddens[0] if len(hiddens) == 1
              else jnp.concatenate(hiddens, axis=1))
    last_h = jnp.take(hidden[0], prompt_len - 1, axis=0)     # [d]
    embedding = params["embed"]["embedding"]
    last = jnp.dot(embedding.astype(jnp.float32),
                   last_h.astype(jnp.float32))               # [vocab]
    return cache, last


@functools.partial(jax.jit, static_argnames=("model",
                                             "prefill_chunk"))
def _prefill_dense(model, prefill_chunk, params, cache, slot, prompt,
                   prompt_len):
    """Fill ONE slot's cache region from a prompt [1, L] (batch-1
    forward, scattered into the slot row), returning the last-token
    logits for the first sample. The small cache's write index ran to
    L (the padded length); the slot's index is corrected to the true
    prompt_len. Module-level jit with a static model: same-config
    engines (fleet replicas, draft/target pairs) share one compile
    per length bucket."""
    small, last = _dense_prefill(model, prefill_chunk, params, prompt,
                                 prompt_len)

    def scatter(big, sm, path_key):
        if path_key == "index":
            return big.at[slot].set(prompt_len)
        return big.at[slot].set(sm[0])

    cache = jax.tree_util.tree_map_with_path(
        lambda kp, big, sm: scatter(
            big, sm, kp[-1].key if hasattr(kp[-1], "key")
            else str(kp[-1])),
        cache, small)
    return cache, last


@functools.partial(jax.jit, static_argnames=("model", "prefill_chunk",
                                             "page"))
def _prefill_paged(model, prefill_chunk, page, params, cache, slot,
                   prompt, table_row, prompt_len):
    """Paged variant: dense batch-1 prefill, rows scattered
    page-by-page into the slot's allocated pages; the slot's
    block-table row and length are set in every layer's cache copy.
    Full pages are written unconditionally: blocks past the
    allocation point at the scratch page (which absorbs
    padded-garbage writes), and partial-page garbage is
    masked-on-read via the true length."""
    small, last = _dense_prefill(model, prefill_chunk, params, prompt,
                                 prompt_len)
    # Bucket blocks, static (ceil: a bucket smaller than one page
    # still needs its first page written; the small cache has
    # max_decode_len >= n_blocks*page rows).
    n_blocks = -(-prompt.shape[1] // page)

    def scatter(big, sm):
        if isinstance(big, dict) and "k_pages" in big:
            kp, vp = big["k_pages"], big["v_pages"]
            for b in range(n_blocks):
                krows = sm["k"][0, b * page:(b + 1) * page]
                vrows = sm["v"][0, b * page:(b + 1) * page]
                kp = kp.at[table_row[b]].set(krows.astype(kp.dtype))
                vp = vp.at[table_row[b]].set(vrows.astype(vp.dtype))
            out = {
                "k_pages": kp, "v_pages": vp,
                "block_table":
                    big["block_table"].at[slot].set(table_row),
                "length":
                    big["length"].at[slot].set(prompt_len),
            }
            if "k_page_scales" in big:
                # int8 pool: the dense prefill cache is int8 too
                # (same kv_cache_dtype), so its rows and scales route
                # straight into the page pool.
                ksc = big["k_page_scales"]
                vsc = big["v_page_scales"]
                for b in range(n_blocks):
                    ksc = ksc.at[table_row[b]].set(
                        sm["k_scale"][0, b * page:(b + 1) * page])
                    vsc = vsc.at[table_row[b]].set(
                        sm["v_scale"][0, b * page:(b + 1) * page])
                out["k_page_scales"] = ksc
                out["v_page_scales"] = vsc
            return out
        return {key: scatter(big[key], sm[key]) for key in big}

    return scatter(cache, small), last


@functools.partial(jax.jit, static_argnames=("model", "prefill_chunk",
                                             "page"))
def _prefill_paged_shared(model, prefill_chunk, page, params, cache,
                          slot, suffix, prefix_ids, table_row,
                          suffix_row, prefix_len, prompt_len):
    """Shared-prefix paged prefill: the request matched ``prefix_len``
    tokens (a whole number of pages, ids in ``prefix_ids``) in the
    engine's prefix index, so prefill SKIPS them — the forward runs
    only over ``suffix`` [1, S_bucket].

    Mechanics: (1) seed a batch-1 dense cache with the prefix K/V
    gathered straight out of the page pool
    (transformer.prefix_rows_from_pages) and set its write index to
    prefix_len; (2) run the suffix chunks through the model with
    GLOBAL positions prefix_len.. — the multi-token insert path
    attends causally over the seeded prefix exactly as a cold prefill
    would, and in fp32 produces the same bytes (the shared rows ARE
    the rows a cold prefill writes); (3) scatter only the suffix rows
    into the slot's freshly allocated pages (``suffix_row``, scratch-
    padded) and install the full block-table row + true length.

    prefix_len/prompt_len are dynamic (traced), so compiles key on the
    SUFFIX length bucket alone — a 1,000-token cached system prompt
    costs one gather (memory-bound) plus a short-bucket forward
    instead of a long-bucket prefill. prefix_ids is fixed-width
    (max_decode_len/page entries, scratch-padded): the gather reads a
    full cache width of pool rows per layer, which is the memcpy-class
    cost the skipped prefill FLOPs pay for."""
    small = inf.init_cache(model, params, 1)

    def seed(big, sm):
        if isinstance(big, dict) and "k_pages" in big:
            rows = tfm.prefix_rows_from_pages(big, prefix_ids, page)
            nrows = rows["k"].shape[0]
            out = dict(sm)
            out["k"] = sm["k"].at[0, :nrows].set(
                rows["k"].astype(sm["k"].dtype))
            out["v"] = sm["v"].at[0, :nrows].set(
                rows["v"].astype(sm["v"].dtype))
            out["index"] = jnp.full_like(sm["index"], prefix_len)
            if "k_scale" in sm:
                out["k_scale"] = sm["k_scale"].at[0, :nrows].set(
                    rows["k_scale"])
                out["v_scale"] = sm["v_scale"].at[0, :nrows].set(
                    rows["v_scale"])
            return out
        return {key: seed(big[key], sm[key]) for key in sm}

    small = seed(cache, small)
    total = suffix.shape[1]
    chunk = min(prefill_chunk or total, total)
    hiddens = []
    for off in range(0, total, chunk):
        seg = suffix[:, off:off + chunk]
        h, mut = model.apply(
            {"params": params, "cache": small}, seg,
            return_hidden=True,
            positions=prefix_len + jnp.arange(
                off, off + seg.shape[1], dtype=jnp.int32),
            mutable=["cache"])
        small = mut["cache"]
        hiddens.append(h)
    hidden = (hiddens[0] if len(hiddens) == 1
              else jnp.concatenate(hiddens, axis=1))
    last_h = jnp.take(hidden[0], prompt_len - prefix_len - 1, axis=0)
    embedding = params["embed"]["embedding"]
    last = jnp.dot(embedding.astype(jnp.float32),
                   last_h.astype(jnp.float32))
    # Suffix rows live at SMALL-cache rows prefix_len.. — dynamic
    # slices per page. Starts are page-multiples (prefix_len is a
    # whole number of pages), so the only slices that can clamp at
    # the buffer edge are bucket-padding blocks, and those target the
    # scratch page via suffix_row.
    n_blocks = -(-total // page)

    def scatter(big, sm):
        if isinstance(big, dict) and "k_pages" in big:
            kp, vp = big["k_pages"], big["v_pages"]
            for b in range(n_blocks):
                start = prefix_len + b * page
                krows = jax.lax.dynamic_slice_in_dim(
                    sm["k"][0], start, page)
                vrows = jax.lax.dynamic_slice_in_dim(
                    sm["v"][0], start, page)
                kp = kp.at[suffix_row[b]].set(krows.astype(kp.dtype))
                vp = vp.at[suffix_row[b]].set(vrows.astype(vp.dtype))
            out = {
                "k_pages": kp, "v_pages": vp,
                "block_table":
                    big["block_table"].at[slot].set(table_row),
                "length":
                    big["length"].at[slot].set(prompt_len),
            }
            if "k_page_scales" in big:
                ksc = big["k_page_scales"]
                vsc = big["v_page_scales"]
                for b in range(n_blocks):
                    start = prefix_len + b * page
                    ksc = ksc.at[suffix_row[b]].set(
                        jax.lax.dynamic_slice_in_dim(
                            sm["k_scale"][0], start, page))
                    vsc = vsc.at[suffix_row[b]].set(
                        jax.lax.dynamic_slice_in_dim(
                            sm["v_scale"][0], start, page))
                out["k_page_scales"] = ksc
                out["v_page_scales"] = vsc
            return out
        return {key: scatter(big[key], sm[key]) for key in big}

    return scatter(cache, small), last


@dataclasses.dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    # Admission priority among QUEUED requests (higher admits first;
    # ties FIFO). Active slots are never preempted for priority —
    # this orders the wait line, like job.priority orders task
    # queues.
    priority: int = 0
    # Request-level SLO targets (None = best-effort): admission
    # orders same-priority entries by TTFT deadline, deferral guards
    # active slots' TPOT headroom against long prefill stalls, and —
    # when the engine is configured with a shed grace — overload
    # drops the deepest-deadline-violating entries instead of
    # serving them pointlessly late. Per-class defaults come from
    # config (config/settings.py ServingSloSettings); the front end
    # resolves slo_class -> targets before submit.
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    slo_class: str = "standard"


@dataclasses.dataclass
class SpeculativeConfig:
    """Draft-model spec for ENGINE-INTEGRATED speculative decoding
    (the Leviathan draft/verify loop lifted out of
    models/inference.speculative_generate into the continuous batcher):
    each engine step drafts ``gamma`` tokens per active slot with the
    small draft model, verifies every slot's [y, d_1..d_gamma] block
    in ONE batched target forward, then commits/rewinds PER SLOT —
    slots advance 1..gamma+1 tokens per step, so all slot bookkeeping
    is variable-stride. Greedy-exact: outputs equal the
    non-speculative engine's for any draft quality (only throughput
    changes) — bit-exact in fp32 (the equivalence the tests pin);
    at reduced precision the usual multi-token caveat applies (the
    verify forward scores gamma+1 positions in one block, so under
    bf16 an argmax near-tie can resolve differently than single-step
    decode — same as models/inference.speculative_generate, see
    docs/15-serving.md). The draft always uses a dense KV cache
    (O(1) index rewind); the target may be dense or paged."""
    draft_config: tfm.TransformerConfig
    draft_params: object
    gamma: int = 4


@dataclasses.dataclass
class _Slot:
    request: Optional[Request] = None
    generated: list[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _QueueEntry:
    """A queued request, plus the tokens it had already generated if
    it was preempted (overcommit mode): resumption re-prefills
    prompt + resumed in one pass and continues decoding — the greedy
    continuation is identical to the uninterrupted run."""
    request: Request
    resumed: list[int] = dataclasses.field(default_factory=list)
    # Monotonic submission stamp: the anchor for TTFT deadlines
    # (EDF ordering within a priority class, overload shedding).
    submitted_at: float = 0.0


class ContinuousBatcher:
    """Slot-based continuous batching engine.

    Usage:
        engine = ContinuousBatcher(config, params, num_slots=8,
                                   max_decode_len=2048)
        engine.submit(Request("r1", prompt_ids, max_new_tokens=128))
        while engine.pending():
            for request_id, tokens in engine.step():
                ...  # finished request
    """

    def __init__(self, config: tfm.TransformerConfig, params,
                 num_slots: int, max_decode_len: int,
                 sampling: inf.SamplingConfig = inf.SamplingConfig(),
                 seed: int = 0,
                 kv_page_size: Optional[int] = None,
                 kv_num_pages: Optional[int] = None,
                 overcommit: bool = False,
                 prefill_chunk: Optional[int] = None,
                 on_token: Optional[
                     Callable[[str, int, int], None]] = None,
                 speculative: Optional[SpeculativeConfig] = None,
                 prefix_cache: bool = True,
                 slo_shed_grace_ms: Optional[float] = None,
                 tpot_stall_factor: float = 4.0):
        """kv_page_size enables the PAGED KV cache (vLLM-style): K/V
        live in a shared kv_num_pages-page pool and slots hold block
        tables covering only their live tokens, so HBM is sized for
        aggregate active context instead of
        num_slots * max_decode_len. kv_num_pages defaults to the
        no-deadlock capacity (num_slots * ceil(max_len/page)).

        Admission policy for a smaller pool:
          - overcommit=False (default): RESERVATION — admission takes
            each request's worst-case page count (prompt +
            max_new_tokens) up front, so decode can never exhaust the
            pool, at the cost of admitting fewer concurrent requests
            than actual usage would allow.
          - overcommit=True: PREEMPTION — admission takes only the
            prompt's pages (+1 headroom); when a decode step needs a
            page and none is free, the active slot with the fewest
            generated tokens is preempted (pages reclaimed, request
            re-queued at the head) and later resumed by re-prefilling
            prompt + already-generated tokens. Short actual
            generations then share a pool far below worst-case.

        prefix_cache (paged mode only) enables CROSS-REQUEST PREFIX
        REUSE: every full prompt page is indexed by a chained content
        hash at prefill, and a later request whose prompt starts with
        the same pages pins them (refcounted) instead of recomputing
        — its prefill runs only over the suffix
        (_prefill_paged_shared). Unreferenced indexed pages park in
        an LRU and are evicted only when the allocator runs dry, so
        the reuse window is however much pool slack the workload
        leaves. Greedy outputs are unchanged (the shared rows are the
        bytes a cold prefill writes).

        slo_shed_grace_ms, when set, arms overload shedding: a queued
        request whose TTFT deadline has been missed by more than the
        grace is dropped (deepest violation first) instead of served
        pointlessly late — on_shed fires and the front end surfaces
        the drop as an error. tpot_stall_factor bounds admission's
        prefill-stall tolerance: a prefill predicted to stall active
        decodes longer than factor * (tightest active TPOT target) is
        deferred unless the candidate's own TTFT deadline is about to
        blow.

        prefill_chunk caps the CHUNKED PREFILL segment length: long
        prompts prefill in fixed-size multi-token inserts (each chunk
        attends causally over the cache, so the math is identical to
        one full-sequence pass) — the peak prefill score tensor
        shrinks from O(L * max_decode_len) to
        O(chunk * max_decode_len) (decode-path attention spans the
        full cache width). Compilation stays per length bucket (the
        chunk loop unrolls inside the bucket's jit). Use a power of
        two so chunks divide the power-of-two length buckets
        exactly."""
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        self.config = inf.decode_config(config, max_decode_len)
        self.paged = kv_page_size is not None
        self.overcommit = overcommit
        # Observer called as (request_id, token, index) the moment a
        # token is generated (index 0 = the prefill-sampled first
        # token) — the TTFT/TPOT measurement point for serving front
        # ends. Runs on the engine's stepping thread.
        self.on_token = on_token
        # Observer called as (request_id,) the moment a queued
        # request wins a slot, just before its prefill runs — the
        # queued->prefill boundary of the request's trace span chain
        # (models/server.py). Runs on the engine's stepping thread.
        self.on_admit: Optional[Callable[[str], None]] = None
        self.preemptions = 0
        self.speculative = speculative
        self.gamma = speculative.gamma if speculative else 0
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        if speculative is not None:
            if speculative.gamma < 1:
                raise ValueError(
                    f"speculative gamma must be >= 1, got "
                    f"{speculative.gamma}")
            if sampling.temperature > 0:
                raise ValueError(
                    "speculative serving is greedy-exact (draft "
                    "acceptance compares argmax chains); it requires "
                    "temperature == 0 sampling")
            if getattr(speculative.draft_config, "kv_page_size", None):
                raise ValueError(
                    "the draft model uses a dense KV cache (O(1) "
                    "index rewind); clear kv_page_size on the draft "
                    "config")
            if speculative.draft_config.vocab_size != \
                    config.vocab_size:
                raise ValueError(
                    "draft/target vocab_size must match (acceptance "
                    "compares token ids)")
        if overcommit and not self.paged:
            raise ValueError("overcommit requires the paged KV cache "
                             "(kv_page_size)")
        if self.paged:
            if max_decode_len % kv_page_size:
                raise ValueError("max_decode_len must be a multiple "
                                 "of kv_page_size")
            if kv_num_pages is None:
                kv_num_pages = num_slots * (
                    max_decode_len // kv_page_size)
            self.config = dataclasses.replace(
                self.config, kv_page_size=kv_page_size,
                kv_num_pages=kv_num_pages, spec_window=self.gamma)
            self.page_size = kv_page_size
            # spec_window widens the table so a speculative verify
            # block starting near max_decode_len spills its tail
            # writes onto scratch-backed entries instead of clamping
            # onto a real page (transformer._decode_attend_paged).
            self.max_blocks = (max_decode_len + self.gamma
                               + kv_page_size - 1) // kv_page_size
            self._free_pages = list(range(kv_num_pages))
            # Reservation budget: admission reserves each request's
            # WORST-CASE page count up front (prompt + max_new_tokens)
            # so lazy growth during decode can never deadlock two
            # half-grown slots against each other.
            self._avail_pages = kv_num_pages
            self._total_pages = kv_num_pages
            self._slot_reserved = [0] * num_slots
            # The decode step runs the full slot batch, so INACTIVE
            # slots keep writing (masked-on-read) K/V through their
            # block tables. Their tables must therefore never point at
            # allocatable pages: one extra physical SCRATCH page (index
            # kv_num_pages) absorbs those writes, and freed slots'
            # table rows reset to it.
            self._scratch_page = kv_num_pages
            self.config = dataclasses.replace(
                self.config, kv_num_pages=kv_num_pages + 1)
            self._table = np.full((num_slots, self.max_blocks),
                                  self._scratch_page, np.int32)
            self._slot_pages: list[list[int]] = [
                [] for _ in range(num_slots)]
            # Prefix-cache state. Page lifecycle: FREE (_free_pages)
            # -> OWNED (a slot's private _slot_pages) -> PINNED
            # (indexed, refcount >= 1, referenced via _slot_shared)
            # -> LRU (indexed, refcount 0, evictable) -> FREE.
            # Accounting invariant: _avail_pages =
            # total - pinned - sum(_slot_reserved) — LRU pages still
            # count as available because _alloc_page can always evict
            # them; pinned pages cannot be reclaimed while referenced.
            self._slot_shared: list[list[int]] = [
                [] for _ in range(num_slots)]
            self._prefix_index: dict[bytes, int] = {}
            self._page_key: dict[int, bytes] = {}
            self._page_ref: dict[int, int] = {}
            self._lru: "collections.OrderedDict[int, None]" = \
                collections.OrderedDict()
        self.prefix_cache = bool(prefix_cache) and self.paged
        self.prefix_lookups = 0
        self.prefix_hit_pages = 0
        self.prefix_hit_tokens = 0
        self.prefix_total_tokens = 0
        self.prefix_published = 0
        self.prefix_evictions = 0
        # SLO scheduling state: live EWMA estimates of prefill cost
        # per bucket token and of the decode step feed admission's
        # stall prediction; sheds/deferrals are the overload
        # counters per-class attainment reporting builds on.
        self.slo_shed_grace_ms = slo_shed_grace_ms
        self.tpot_stall_factor = tpot_stall_factor
        self.slo_sheds = 0
        self.sheds_by_class: dict[str, int] = {}
        self.slo_deferrals = 0
        self.on_shed: Optional[Callable[[str, str], None]] = None
        # Drain mode (serving fault tolerance): once set, _admit
        # refuses to seat new work — active decodes run to completion
        # while the queue is handed back to the caller for failover.
        self.draining = False
        self._prefill_ms_per_token: Optional[float] = None
        self._step_ms: Optional[float] = None
        self._timed_buckets: set = set()
        self._step_samples = 0
        self.model = tfm.TransformerLM(self.config)
        self.params = params
        self.num_slots = num_slots
        self.max_decode_len = max_decode_len
        self.sampling = sampling
        self.cache = inf.init_cache(self.model, params, num_slots)
        if self.paged:
            # Fresh caches default block tables to zeros (a REAL
            # page); point every slot at the scratch page before any
            # step runs.
            self._push_tables()
        self._slots = [_Slot() for _ in range(num_slots)]
        self._queue: list[_QueueEntry] = []
        self._tokens = jnp.zeros((num_slots, 1), jnp.int32)
        self._positions = jnp.zeros((num_slots,), jnp.int32)
        self._active = jnp.zeros((num_slots,), jnp.bool_)
        self._key = jax.random.PRNGKey(seed)

        self._decode_step = functools.partial(
            _decode_step, self.model, self.sampling)

        # Prefill always runs on a DENSE batch-1 decode model sharing
        # the params; paged mode then scatters its rows into the
        # slot's allocated pages. The prefill fns are module-level
        # static-model jits (the speculative path binds the same
        # machinery to the DRAFT model, and same-config engines share
        # compiles).
        dense_model = tfm.TransformerLM(
            inf.decode_config(config, max_decode_len))
        page = getattr(self, "page_size", 0)
        self._prefill = functools.partial(
            _prefill_dense, dense_model, self.prefill_chunk)
        self._prefill_paged = functools.partial(
            _prefill_paged, dense_model, self.prefill_chunk, page)
        self._prefill_shared = functools.partial(
            _prefill_paged_shared, dense_model, self.prefill_chunk,
            page)

        if speculative is not None:
            # Draft engine state: a dense cache with gamma+1 extra
            # rows so a draft block starting at max_decode_len-2 never
            # wraps (the target cache needs no extra rows — its
            # out-of-bounds tail scatters drop, and every key a
            # COMMITTED query reads is in bounds by construction).
            draft_model = tfm.TransformerLM(inf.decode_config(
                speculative.draft_config,
                max_decode_len + self.gamma + 1))
            self._draft_params = speculative.draft_params
            self._draft_cache = inf.init_cache(
                draft_model, speculative.draft_params, num_slots)
            self._draft_prefill = functools.partial(
                _prefill_dense, draft_model, self.prefill_chunk)
            self._spec_step = functools.partial(
                _speculative_step, self.model, draft_model,
                self.gamma)

    # ------------------------------ public -----------------------------

    def warmup_buckets(self) -> list[int]:
        """Every prefill compile bucket this engine can serve,
        DERIVED from _bucket_length (the one source of the bucket
        rule): walk each bucket's successor until the cap."""
        buckets = [self._bucket_length(1)]
        while buckets[-1] < self.max_decode_len:
            buckets.append(self._bucket_length(buckets[-1] + 1))
        return buckets

    def warmup(self, prompt_len: Optional[int] = None,
               max_new_tokens: int = 2) -> list[int]:
        """Drive throwaway requests through prefill + decode so the
        jit compiles happen before real traffic, recorded as an
        engine warm-up goodput phase (compile-leg badput; see
        goodput/accounting.py) with the persistent compile cache's
        hit/saved detail when one is enabled. By default EVERY prefill
        length bucket up to max_decode_len is warmed — one request per
        bucket, drained sequentially — so the first long-prompt
        request never pays a mid-traffic compile; the decode step and,
        when a draft model is configured, the speculative draft/verify
        paths compile on the first request. ``prompt_len`` pins a
        single warm-up request instead. Serving front ends call this
        before accepting load so warm-up never pollutes TTFT. Returns
        the buckets warmed."""
        from batch_shipyard_tpu.compilecache import (
            manager as cc_manager)
        from batch_shipyard_tpu.goodput import events as goodput_events
        if prompt_len is not None:
            lengths = [prompt_len]
        else:
            lengths = [min(bucket,
                           self.max_decode_len - max_new_tokens)
                       for bucket in self.warmup_buckets()]
            if self.paged:
                # A deliberately tight page pool (overcommit sizing)
                # cannot admit the longest buckets' worst case: skip
                # them rather than fail startup — they compile on
                # first real (admittable) use, as before.
                lengths = [
                    length for length in lengths
                    if -(-(length + max_new_tokens)
                         // self.page_size) <= self._total_pages]
        warmed: list[int] = []

        def drain(length: int) -> None:
            self.submit(Request(
                request_id=f"__warmup__{uuid.uuid4().hex[:8]}",
                prompt=[(i % 7) + 1 for i in range(length)],
                max_new_tokens=max_new_tokens))
            while self.pending():
                self.step()

        with goodput_events.phase(goodput_events.PROGRAM_WARMUP,
                                  what="serving_engine",
                                  buckets=len(lengths)) as attrs, \
                cc_manager.tracked(attrs, "serving_warmup"):
            for length in lengths:
                if self.prefix_cache:
                    # The warm-up prompts share prefixes, so with the
                    # index live a long bucket would match the
                    # previous bucket's published pages and compile
                    # the SHARED path instead of its cold prefill —
                    # and the first novel long prompt in real traffic
                    # would then pay that compile mid-measurement.
                    # Match against an empty index so every bucket
                    # compiles cold.
                    self.prefix_cache_clear()
                drain(length)
                warmed.append(self._bucket_length(length))
            if self.prefix_cache and len(lengths) > 1:
                # Second pass compiles the shared-prefill suffix
                # buckets: starting from an empty index, each chained
                # prompt matches the full pages the previous bucket's
                # request published, leaving only the suffix to
                # prefill.
                self.prefix_cache_clear()
                for length in lengths:
                    drain(length)
        if self.prefix_cache:
            # Real traffic should start against an empty index, and
            # the stats should describe real traffic only — not the
            # warm-up's synthetic lookups and publishes.
            self.prefix_cache_clear()
            self.prefix_lookups = 0
            self.prefix_hit_pages = 0
            self.prefix_hit_tokens = 0
            self.prefix_total_tokens = 0
            self.prefix_published = 0
            self.prefix_evictions = 0
        return warmed

    def precompile(self) -> int:
        """AOT warm start from shapes — no throwaway requests: lower +
        compile the decode step (or the speculative draft/verify step)
        and every prefill bucket against ShapeDtypeStruct abstract
        inputs. The executables are discarded; the value is the
        PERSISTENT compilation cache (compilecache/manager.py) they
        populate, which turns the first real request's jit compiles
        into fast deserializes — so enable the cache first, or this
        compiles twice for nothing. Returns the number of functions
        compiled."""
        import jax as jax_mod

        from batch_shipyard_tpu.compilecache import aot
        from batch_shipyard_tpu.compilecache import (
            manager as cc_manager)
        from batch_shipyard_tpu.goodput import events as goodput_events
        count = 0
        with goodput_events.phase(goodput_events.PROGRAM_WARMUP,
                                  what="serving_aot") as attrs, \
                cc_manager.tracked(attrs, "serving_precompile"):
            params_abs = aot.abstractify(self.params)
            cache_abs = aot.abstractify(self.cache)
            tokens_abs = jax_mod.ShapeDtypeStruct(
                (self.num_slots, 1), jnp.int32)
            pos_abs = jax_mod.ShapeDtypeStruct((self.num_slots,),
                                               jnp.int32)
            active_abs = jax_mod.ShapeDtypeStruct((self.num_slots,),
                                                  jnp.bool_)
            if self.speculative is not None:
                _speculative_step.lower(
                    self.model, self._spec_step.args[1], self.gamma,
                    params_abs, aot.abstractify(self._draft_params),
                    cache_abs, aot.abstractify(self._draft_cache),
                    tokens_abs, pos_abs, active_abs).compile()
            else:
                key_abs = aot.abstractify(self._key)
                _decode_step.lower(
                    self.model, self.sampling, params_abs, cache_abs,
                    tokens_abs, pos_abs, active_abs,
                    key_abs).compile()
            count += 1
            dense_model = self._prefill.args[0]
            for bucket in self.warmup_buckets():
                prompt_abs = jax_mod.ShapeDtypeStruct((1, bucket),
                                                      jnp.int32)
                if self.paged:
                    row_abs = jax_mod.ShapeDtypeStruct(
                        (self.max_blocks,), jnp.int32)
                    _prefill_paged.lower(
                        dense_model, self.prefill_chunk,
                        self.page_size, params_abs, cache_abs, 0,
                        prompt_abs, row_abs, bucket).compile()
                else:
                    _prefill_dense.lower(
                        dense_model, self.prefill_chunk, params_abs,
                        cache_abs, 0, prompt_abs, bucket).compile()
                count += 1
                if self.speculative is not None:
                    # _admit prefills the DRAFT cache too (the
                    # spec-step invariant) — a distinct compile per
                    # bucket that would otherwise hit mid-traffic.
                    _prefill_dense.lower(
                        self._draft_prefill.args[0],
                        self.prefill_chunk,
                        aot.abstractify(self._draft_params),
                        aot.abstractify(self._draft_cache), 0,
                        prompt_abs, bucket).compile()
                    count += 1
        return count

    def submit(self, request: Request,
               resumed: Optional[list[int]] = None) -> None:
        """Enqueue a request. ``resumed`` carries tokens already
        emitted by a prior (killed or drained) replica: the entry
        re-prefills prompt+resumed in one pass and decoding continues
        from there, so a greedy stream is byte-identical to an
        uninterrupted run. Refused while draining — the caller must
        fail over to a sibling."""
        if self.draining:
            raise ValueError(
                f"{request.request_id}: engine is draining")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"{request.request_id}: max_new_tokens must be >= 1")
        if not request.prompt:
            raise ValueError(
                f"{request.request_id}: prompt must be non-empty")
        resumed = [int(t) for t in (resumed or [])]
        if len(resumed) >= request.max_new_tokens:
            raise ValueError(
                f"{request.request_id}: resumed tokens "
                f"{len(resumed)} >= max_new_tokens "
                f"{request.max_new_tokens} — nothing left to decode")
        if self.paged:
            worst = -(-(len(request.prompt) + request.max_new_tokens)
                      // self.page_size)
            if worst > self._total_pages:
                raise ValueError(
                    f"{request.request_id}: worst-case page need "
                    f"{worst} exceeds the pool ({self._total_pages} "
                    f"pages) — it could never admit")
        if len(request.prompt) + request.max_new_tokens > \
                self.max_decode_len:
            raise ValueError(
                f"{request.request_id}: prompt+generation "
                f"{len(request.prompt)}+{request.max_new_tokens} "
                f"exceeds max_decode_len {self.max_decode_len}")
        self._enqueue(_QueueEntry(request, resumed=resumed,
                                  submitted_at=time.monotonic()))

    def pending(self) -> int:
        return len(self._queue) + sum(
            1 for s in self._slots if s.request is not None)

    def drain(self) -> list[str]:
        """Flip the engine into drain mode: _admit stops seating new
        work, queued entries (which hold no pages) are evicted and
        their ids returned so the front end can 503 their waiters for
        router failover, and active decodes keep stepping until they
        finish (or the front end's grace deadline cancels them).
        Idempotent; must be called from the engine's stepping
        thread — it mutates the queue like _admit does."""
        self.draining = True
        evicted = [e.request.request_id for e in self._queue]
        self._queue.clear()
        return evicted

    def active_request_ids(self) -> list[str]:
        """Ids currently decoding in a slot (in-flight work a drain
        lets run to completion)."""
        return [s.request.request_id for s in self._slots
                if s.request is not None]

    def cancel(self, request_id: str) -> bool:
        """Abort a queued or actively-decoding request (the vLLM-class
        abort operation). Queued entries are removed; an active slot
        is freed immediately (its pages return to the pool). Must be
        called from the engine's stepping thread — it mutates slot
        state like step() does. Returns False when the id is unknown
        (already finished)."""
        for k, entry in enumerate(self._queue):
            if entry.request.request_id == request_id:
                del self._queue[k]
                return True
        for i, slot in enumerate(self._slots):
            if slot.request is not None and \
                    slot.request.request_id == request_id:
                self._free_slot(i)
                return True
        return False

    def step(self) -> list[tuple[str, list[int]]]:
        """Admit queued requests into free slots, decode for every
        active slot — one token per step, or a gamma-token
        draft/verify block per slot when speculative decoding is
        configured — and emit finished requests."""
        self._admit()
        # Slots whose prefill-sampled first token already satisfied the
        # request (max_new_tokens == 1 or immediate eos) emit without a
        # decode step.
        emitted: list[tuple[str, list[int]]] = []
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None or not slot.generated:
                continue
            last = slot.generated[-1]
            if (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and last == req.eos_id)):
                emitted.append((req.request_id, list(slot.generated)))
                self._free_slot(i)
        if not any(s.request is not None for s in self._slots):
            return emitted
        if self.speculative is not None:
            return emitted + self._step_speculative()
        if self.paged:
            self._grow_pages()
        t0 = time.monotonic()
        self._key, step_key = jax.random.split(self._key)
        self.cache, self._tokens, self._positions, next_tok = \
            self._decode_step(self.params, self.cache, self._tokens,
                              self._positions, self._active, step_key)
        next_host = np.asarray(next_tok)
        self._record_step_time(t0)
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            token = int(next_host[i])
            slot.generated.append(token)
            if self.on_token is not None:
                self.on_token(req.request_id, token,
                              len(slot.generated) - 1)
            done = (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and token == req.eos_id))
            if done:
                emitted.append((req.request_id, list(slot.generated)))
                self._free_slot(i)
        return emitted

    def _step_speculative(self) -> list[tuple[str, list[int]]]:
        """One ragged draft/verify/commit round (see the spec_step
        docstring for the compute): slots advance by different amounts
        per step, so the host bookkeeping below is variable-stride —
        each slot appends its own 1..gamma+1 committed tokens, with
        per-token eos/max_new checks so a slot can stop mid-block."""
        if self.paged:
            self._grow_pages(span=self.gamma)
        t0 = time.monotonic()
        (self.cache, self._draft_cache, self._tokens, self._positions,
         block, a_slot) = self._spec_step(
            self.params, self._draft_params, self.cache,
            self._draft_cache, self._tokens, self._positions,
            self._active)
        block_host = np.asarray(block)
        self._record_step_time(t0)
        a_host = np.asarray(a_slot)
        emitted: list[tuple[str, list[int]]] = []
        n_active = 0
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            n_active += 1
            accepted = int(a_host[i])
            self.spec_accepted += accepted
            for j in range(accepted + 1):
                token = int(block_host[i, j])
                slot.generated.append(token)
                if self.on_token is not None:
                    self.on_token(req.request_id, token,
                                  len(slot.generated) - 1)
                if (len(slot.generated) >= req.max_new_tokens or
                        (req.eos_id is not None and
                         token == req.eos_id)):
                    # Stopped mid-block: the remaining committed
                    # tokens are discarded (their cache rows recycle
                    # with the slot).
                    emitted.append((req.request_id,
                                    list(slot.generated)))
                    self._free_slot(i)
                    break
        self.spec_rounds += 1
        self.spec_proposed += self.gamma * n_active
        return emitted

    def spec_stats(self) -> Optional[dict]:
        """Speculative-decode counters, or None when no draft model
        is configured. acceptance_rate = accepted/proposed is the
        measured draft quality; tokens-per-target-forward is
        1 + acceptance_rate * gamma."""
        if self.speculative is None:
            return None
        return {
            "gamma": self.gamma,
            "rounds": self.spec_rounds,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "acceptance_rate": (
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0),
        }

    def _free_slot(self, i: int) -> None:
        self._slots[i] = _Slot()
        self._active = self._active.at[i].set(False)
        if self.paged:
            self._release_pages(slot=i)
            # The freed slot keeps decoding (masked) in the full-batch
            # step: its table must stop referencing returned pages
            # BEFORE they are reallocated.
            self._table[i] = self._scratch_page
            self._push_tables()

    def _alloc_page(self, grow_slot: Optional[int] = None) -> int:
        """THE single page-allocation path: free list first, then
        LRU-evict an unreferenced indexed page (dropping its index
        entry — a pinned page is never evicted), then, in overcommit
        mode during decode growth, preempt a victim slot. Every page
        a slot's table comes to reference is handed out here;
        _release_pages is the only way back (the serving-page-refcount
        lint rule pins both)."""
        while True:
            if self._free_pages:
                return self._free_pages.pop()
            if self._lru:
                pid, _ = self._lru.popitem(last=False)
                key = self._page_key.pop(pid)
                if self._prefix_index.get(key) == pid:
                    del self._prefix_index[key]
                del self._page_ref[pid]
                self.prefix_evictions += 1
                return pid
            if not self.overcommit or grow_slot is None:
                raise RuntimeError(
                    "paged KV pool exhausted mid-decode; size "
                    "kv_num_pages >= num_slots * max_decode_len / "
                    "page_size to rule this out, or enable "
                    "overcommit=True for preemption")
            self._preempt(exclude=grow_slot)

    def _release_pages(self, slot: Optional[int] = None,
                       pages: Optional[list] = None) -> None:
        """THE single page-release path (the serving-page-refcount
        lint rule's counterpart to _alloc_page). slot=i returns slot
        i's OWNED pages to the free list, drops its SHARED-page
        references (a refcount reaching zero parks the page in the
        LRU — never the free list, so no page is freed while another
        slot's table still reads it), and releases its reservation.
        pages=[...] frees already-unindexed pages directly
        (prefix_cache_clear's evictions)."""
        if pages:
            self._free_pages.extend(pages)
        if slot is None:
            return
        self._free_pages.extend(self._slot_pages[slot])
        self._slot_pages[slot] = []
        for pid in self._slot_shared[slot]:
            self._page_ref[pid] -= 1
            if self._page_ref[pid] == 0:
                self._lru[pid] = None
                self._avail_pages += 1
        self._slot_shared[slot] = []
        self._avail_pages += self._slot_reserved[slot]
        self._slot_reserved[slot] = 0

    def prefix_cache_clear(self) -> int:
        """Evict every UNREFERENCED indexed page back to the free
        list (pinned pages stay — they are still read by active
        slots). Returns the number of pages reclaimed."""
        dropped = []
        while self._lru:
            pid, _ = self._lru.popitem(last=False)
            key = self._page_key.pop(pid)
            if self._prefix_index.get(key) == pid:
                del self._prefix_index[key]
            del self._page_ref[pid]
            dropped.append(pid)
        self._release_pages(pages=dropped)
        return len(dropped)

    def prefix_stats(self) -> Optional[dict]:
        """Prefix-cache counters, or None when disabled. hit_rate is
        TOKEN-level: cached prompt tokens / total prompt tokens seen
        by paged admission — the fraction of prefill work the index
        converted into a gather."""
        if not self.prefix_cache:
            return None
        return {
            "lookups": self.prefix_lookups,
            "hit_pages": self.prefix_hit_pages,
            "hit_tokens": self.prefix_hit_tokens,
            "total_prompt_tokens": self.prefix_total_tokens,
            "hit_rate": (
                self.prefix_hit_tokens / self.prefix_total_tokens
                if self.prefix_total_tokens else 0.0),
            "indexed_pages": len(self._page_ref),
            "lru_pages": len(self._lru),
            "published_pages": self.prefix_published,
            "evictions": self.prefix_evictions,
        }

    def slo_stats(self) -> dict:
        """SLO scheduling counters + the live cost estimates
        admission decides with."""
        return {
            "sheds": self.slo_sheds,
            "sheds_by_class": dict(self.sheds_by_class),
            "deferrals": self.slo_deferrals,
            "prefill_ms_per_token": self._prefill_ms_per_token,
            "step_ms": self._step_ms,
        }

    def _grow_pages(self, span: int = 0) -> None:
        """Allocate pages so every active slot's table covers its next
        write positions pos..min(pos+span, total-1) — span=0 is the
        plain one-token decode step (at most one new block per slot);
        span=gamma is the speculative verify block, which can cross
        several page boundaries in one step. A slot's block count is
        shared prefix pages + owned pages; growth only ever appends
        OWNED pages (decode writes land strictly past the shared
        prefix). Allocation is capped at the slot's worst-case commit
        range (speculative tail writes past it land on the scratch
        page via the table default), so it never exceeds the
        admission reservation. Pushes the updated tables into every
        layer's cache copy. In overcommit mode an empty free list
        preempts a victim instead of raising (a preempted victim's
        request empties, so the loop skips it)."""
        positions = np.asarray(self._positions)
        changed = False
        for i in range(self.num_slots):
            if self._slots[i].request is None:
                continue
            req = self._slots[i].request
            total = len(req.prompt) + req.max_new_tokens
            pos = int(positions[i])
            needed = min(pos + span, total - 1) // self.page_size + 1
            while (len(self._slot_shared[i]) +
                   len(self._slot_pages[i])) < needed:
                block = (len(self._slot_shared[i]) +
                         len(self._slot_pages[i]))
                pagenum = self._alloc_page(grow_slot=i)
                self._slot_pages[i].append(pagenum)
                self._table[i, block] = pagenum
                changed = True
        if changed:
            self._push_tables()

    def _preempt(self, exclude: int) -> int:
        """Evict the active slot with the fewest generated tokens
        (cheapest re-prefill), reclaim its pages, and re-queue its
        request AT THE HEAD with its generated-so-far tokens so
        resumption re-prefills prompt+generated and continues — the
        greedy continuation is unchanged. Returns the victim index."""
        candidates = [
            j for j in range(self.num_slots)
            if j != exclude and self._slots[j].request is not None]
        if not candidates:
            raise RuntimeError(
                "paged KV pool exhausted with no preemptible slot — "
                "a single request's live context exceeds the pool")
        victim = min(candidates,
                     key=lambda j: len(self._slots[j].generated))
        slot = self._slots[victim]
        # Preempted work resumes at the HEAD of its own priority
        # class: ahead of waiting peers (it owns partial progress) but
        # never ahead of strictly higher-priority entries — a plain
        # head insert would let a low-priority victim starve a queued
        # high-priority request under sustained page pressure.
        entry = _QueueEntry(slot.request, list(slot.generated))
        pos = 0
        while (pos < len(self._queue) and
               self._queue[pos].request.priority >
               slot.request.priority):
            pos += 1
        self._queue.insert(pos, entry)
        self.preemptions += 1
        self._free_slot(victim)
        return victim

    def _push_tables(self) -> None:
        """Write the canonical block table into every layer's cache
        copy."""
        table = jnp.asarray(self._table)

        def push(leaf_dict):
            if isinstance(leaf_dict, dict) and \
                    "block_table" in leaf_dict:
                return {**leaf_dict, "block_table": table}
            if isinstance(leaf_dict, dict):
                return {k: push(v) for k, v in leaf_dict.items()}
            return leaf_dict

        self.cache = push(self.cache)

    # ----------------------------- internal ----------------------------

    def _bucket_length(self, n: int) -> int:
        """Round a prompt length up to its compile bucket (the next
        power of two, floored at 16, capped at max_decode_len): one
        prefill compile per bucket instead of per distinct length."""
        bucket = 16
        while bucket < n:
            bucket *= 2
        return min(bucket, self.max_decode_len)

    def _enqueue(self, entry: "_QueueEntry") -> None:
        """Insert keeping the queue sorted by descending priority,
        then earliest TTFT deadline within a priority class (EDF;
        entries without a target sort last and stay FIFO among
        themselves — with no SLO targets anywhere this is exactly
        the old priority+FIFO order)."""
        priority = entry.request.priority
        deadline = self._ttft_deadline(entry)
        deadline = float("inf") if deadline is None else deadline
        for k in range(len(self._queue) - 1, -1, -1):
            other = self._queue[k]
            other_deadline = self._ttft_deadline(other)
            if other_deadline is None:
                other_deadline = float("inf")
            if (other.request.priority > priority or
                    (other.request.priority == priority and
                     other_deadline <= deadline)):
                self._queue.insert(k + 1, entry)
                return
        self._queue.insert(0, entry)

    def _ttft_deadline(self, entry: "_QueueEntry") -> Optional[float]:
        """Absolute (monotonic-clock) TTFT deadline, or None when the
        request carries no target."""
        target = entry.request.ttft_target_ms
        if target is None:
            return None
        return entry.submitted_at + target / 1000.0

    def _shed_expired(self, now: float) -> None:
        """Overload shedding (armed by slo_shed_grace_ms): drop every
        queued entry whose TTFT deadline is blown by more than the
        grace, deepest violation first — serving it would be pure
        badput while fresher requests still have budget. Preempted
        (resumed) entries are exempt: their first token already
        shipped, so their TTFT is history and their partial work
        would be wasted."""
        if self.slo_shed_grace_ms is None or self.draining:
            # Draining owns the queue: drain() already evicted it for
            # failover, and anything a draining replica can still
            # finish must not be shed out from under the router.
            return
        while True:
            worst_k, worst_over = None, 0.0
            for k, entry in enumerate(self._queue):
                if entry.resumed:
                    continue
                deadline = self._ttft_deadline(entry)
                if deadline is None:
                    continue
                over = ((now - deadline) * 1000.0 -
                        self.slo_shed_grace_ms)
                if over > worst_over:
                    worst_k, worst_over = k, over
            if worst_k is None:
                return
            entry = self._queue.pop(worst_k)
            self.slo_sheds += 1
            cls = entry.request.slo_class
            self.sheds_by_class[cls] = \
                self.sheds_by_class.get(cls, 0) + 1
            if self.on_shed is not None:
                self.on_shed(entry.request.request_id,
                             "ttft deadline exceeded")

    def _should_defer(self, entry: "_QueueEntry",
                      now: float) -> bool:
        """Batch-composition guard: admitting a long prompt stalls
        every active decode for its whole prefill. When that
        predicted stall (live EWMA prefill cost x bucket length)
        exceeds tpot_stall_factor x the tightest active TPOT target,
        hold the candidate back — unless its own TTFT deadline would
        blow while waiting, at which point its SLO outranks the
        actives' headroom."""
        if self._prefill_ms_per_token is None:
            return False
        targets = [
            s.request.tpot_target_ms for s in self._slots
            if s.request is not None and
            s.request.tpot_target_ms is not None]
        if not targets:
            return False
        tokens = len(entry.request.prompt) + len(entry.resumed)
        if self.prefix_cache:
            # Predict the POST-MATCH suffix cost: a cached prefix
            # pays a gather, not a prefill.
            matched = self._match_prefix(self._page_keys(
                entry.request.prompt + entry.resumed), tokens)
            tokens -= len(matched) * self.page_size
        stall = self._bucket_length(tokens) * \
            self._prefill_ms_per_token
        if stall <= min(targets) * self.tpot_stall_factor:
            return False
        deadline = self._ttft_deadline(entry)
        if deadline is not None and \
                now + stall / 1000.0 >= deadline:
            return False
        return True

    def _page_keys(self, tokens: list[int]) -> list[bytes]:
        """Chained content hash per FULL page: key_b covers tokens
        [0, (b+1)*page) via H(key_{b-1} || tokens of page b), so a
        key identifies the entire prefix up to its page boundary —
        matching never needs to compare token ids, and equal pages
        under different prefixes never collide."""
        keys: list[bytes] = []
        prev = b""
        page = self.page_size
        for b in range(len(tokens) // page):
            digest = hashlib.blake2b(
                prev + np.asarray(tokens[b * page:(b + 1) * page],
                                  np.int64).tobytes(),
                digest_size=16).digest()
            keys.append(digest)
            prev = digest
        return keys

    def _match_prefix(self, keys: list[bytes],
                      num_tokens: int) -> list[int]:
        """Longest indexed page chain, capped so at least one suffix
        token remains (the first sample needs real last-token logits
        from a forward)."""
        limit = (num_tokens - 1) // self.page_size
        matched: list[int] = []
        for b in range(min(len(keys), limit)):
            pid = self._prefix_index.get(keys[b])
            if pid is None:
                break
            matched.append(pid)
        return matched

    def _publish_pages(self, i: int, keys: list[bytes], m: int,
                       row: np.ndarray, num_tokens: int) -> None:
        """Index this admission's fresh FULL pages under their chain
        keys so later same-prefix requests can share them. A
        published page moves from the slot's OWNED list into its
        SHARED set with refcount 1 (held by this slot until it
        frees): pinned grows by one while the slot's reservation
        shrinks by one, so availability is unchanged. Only full
        pages publish — the partial tail stays owned (copy-on-extend:
        decode keeps writing into it privately)."""
        full = num_tokens // self.page_size
        for b in range(m, full):
            key = keys[b]
            if key in self._prefix_index:
                # Duplicate content (an exact-length twin admitted in
                # the same drain could not match its own final full
                # page): keep this copy private rather than aliasing
                # two owners onto one index entry.
                continue
            pid = int(row[b])
            self._slot_pages[i].remove(pid)
            self._slot_shared[i].append(pid)
            self._prefix_index[key] = pid
            self._page_key[pid] = key
            self._page_ref[pid] = 1
            if self.overcommit:
                self._avail_pages -= 1
            else:
                self._slot_reserved[i] -= 1
            self.prefix_published += 1

    def _record_prefill_time(self, key, t0: float,
                             n_tokens: int) -> None:
        """EWMA prefill cost per bucket token; the first sample of
        each compile bucket is discarded (it measures jit
        compilation, not prefill)."""
        dt_ms = (time.monotonic() - t0) * 1000.0
        if key not in self._timed_buckets:
            self._timed_buckets.add(key)
            return
        per_token = dt_ms / max(1, n_tokens)
        if self._prefill_ms_per_token is None:
            self._prefill_ms_per_token = per_token
        else:
            self._prefill_ms_per_token = (
                0.7 * self._prefill_ms_per_token + 0.3 * per_token)

    def _record_step_time(self, t0: float) -> None:
        """EWMA decode-step wall time (the engine-side TPOT floor);
        the first sample is discarded as compile."""
        dt_ms = (time.monotonic() - t0) * 1000.0
        self._step_samples += 1
        if self._step_samples == 1:
            return
        if self._step_ms is None:
            self._step_ms = dt_ms
        else:
            self._step_ms = 0.7 * self._step_ms + 0.3 * dt_ms

    def _admit(self) -> None:
        if self.draining:
            # Drain ladder: no new admissions once the preempt/evict
            # notice lands — active slots finish, the queue was
            # already evicted by drain().
            return
        now = time.monotonic()
        self._shed_expired(now)
        for i, slot in enumerate(self._slots):
            if slot.request is not None or not self._queue:
                continue
            entry = self._queue[0]
            req = entry.request
            if self._should_defer(entry, now):
                # Head-of-line hold: admitting now would stall active
                # decodes past their TPOT headroom.
                self.slo_deferrals += 1
                break
            # Resumed (preempted) requests re-prefill prompt + what
            # they had already generated, in one batched pass.
            tokens = req.prompt + entry.resumed
            bucket = self._bucket_length(len(tokens))
            padded = tokens + [0] * (bucket - len(tokens))
            prompt = jnp.asarray([padded], jnp.int32)
            t0 = time.monotonic()
            timed_key = ("dense", bucket)
            timed_tokens = bucket
            if self.paged:
                blocks_needed = -(-len(tokens) // self.page_size)
                remaining = req.max_new_tokens - len(entry.resumed)
                worst = -(-(len(tokens) + remaining)
                          // self.page_size)
                keys: list[bytes] = []
                matched: list[int] = []
                if self.prefix_cache:
                    keys = self._page_keys(tokens)
                    matched = self._match_prefix(keys, len(tokens))
                m = len(matched)
                lru_m = sum(1 for pid in matched
                            if self._page_ref[pid] == 0)
                if self.overcommit:
                    # Take only the prompt's pages (+1 block of
                    # decode headroom against immediate re-thrash);
                    # exhaustion during decode preempts. Matched
                    # pages cost nothing fresh; pinning an
                    # LRU-parked page consumes one evictable unit.
                    want = min(blocks_needed - m +
                               (1 if remaining else 0), worst - m)
                    if (len(self._free_pages) + len(self._lru)
                            - lru_m) < want:
                        break
                else:
                    if self._avail_pages < (worst - m) + lru_m:
                        # Not enough budget for this request's worst
                        # case: wait for frees rather than risking a
                        # mid-decode exhaustion deadlock between
                        # half-grown slots. The shared prefix
                        # discounts the budget — reuse IS admission
                        # headroom.
                        break
                    self._avail_pages -= worst - m
                    self._slot_reserved[i] = worst - m
                self._queue.pop(0)
                if self.on_admit is not None:
                    self.on_admit(req.request_id)
                # Pin the matched chain: shared pages are immutable
                # (decode writes land strictly past the last full
                # prompt page) and never evictable while referenced.
                for pid in matched:
                    if self._page_ref[pid] == 0:
                        del self._lru[pid]
                        self._avail_pages -= 1
                    self._page_ref[pid] += 1
                self._slot_shared[i] = list(matched)
                if self.prefix_cache:
                    self.prefix_lookups += 1
                    self.prefix_hit_pages += m
                    self.prefix_hit_tokens += m * self.page_size
                    self.prefix_total_tokens += len(tokens)
                fresh = [self._alloc_page()
                         for _ in range(blocks_needed - m)]
                self._slot_pages[i] = fresh
                row = np.full((self.max_blocks,), self._scratch_page,
                              np.int32)
                row[:m] = matched
                row[m:blocks_needed] = fresh
                self._table[i] = row
                if m:
                    prefix_len = m * self.page_size
                    suffix_tokens = tokens[prefix_len:]
                    sbucket = self._bucket_length(len(suffix_tokens))
                    timed_key = ("shared", sbucket)
                    timed_tokens = sbucket
                    suffix = jnp.asarray(
                        [suffix_tokens +
                         [0] * (sbucket - len(suffix_tokens))],
                        jnp.int32)
                    prefix_ids = np.full(
                        (self.max_decode_len // self.page_size,),
                        self._scratch_page, np.int32)
                    prefix_ids[:m] = matched
                    suffix_row = np.full((self.max_blocks,),
                                         self._scratch_page,
                                         np.int32)
                    suffix_row[:blocks_needed - m] = fresh
                    self.cache, last_logits = self._prefill_shared(
                        self.params, self.cache, i, suffix,
                        jnp.asarray(prefix_ids), jnp.asarray(row),
                        jnp.asarray(suffix_row), prefix_len,
                        len(tokens))
                else:
                    timed_key = ("paged", bucket)
                    self.cache, last_logits = self._prefill_paged(
                        self.params, self.cache, i, prompt,
                        jnp.asarray(row), len(tokens))
                if self.prefix_cache:
                    self._publish_pages(i, keys, m, row, len(tokens))
            else:
                self._queue.pop(0)
                if self.on_admit is not None:
                    self.on_admit(req.request_id)
                self.cache, last_logits = self._prefill(
                    self.params, self.cache, i, prompt, len(tokens))
            if self.speculative is not None:
                # The draft cache must hold the same committed prefix
                # (the spec-step invariant); its prefill logits are
                # discarded — the first token is always sampled from
                # the TARGET's prefill.
                self._draft_cache, _ = self._draft_prefill(
                    self._draft_params, self._draft_cache, i, prompt,
                    len(tokens))
            self._key, sample_key = jax.random.split(self._key)
            first = inf._sample(
                last_logits[None].astype(jnp.float32), sample_key,
                self.sampling)
            # The prefill-sampled token IS the next generated token.
            self._slots[i] = _Slot(
                request=req,
                generated=entry.resumed + [int(first[0])])
            if self.on_token is not None:
                self.on_token(req.request_id, int(first[0]),
                              len(entry.resumed))
            self._tokens = self._tokens.at[i, 0].set(first[0])
            self._positions = self._positions.at[i].set(len(tokens))
            self._active = self._active.at[i].set(True)
            # int(first[0]) above forced the prefill to complete, so
            # t0..now is a faithful admission-stall sample.
            self._record_prefill_time(timed_key, t0, timed_tokens)

"""General utilities: logging, shell wrapping, hashing, retry, CIDR math.

Capability parity with the reference's convoy/util.py (logging setup
util.py:86, wrap_commands_in_shell :368, base64/hash helpers :396-509,
subprocess helpers :519-658, CIDR math :659) — re-implemented, not ported.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import ipaddress
import logging
import os
import random
import shlex
import subprocess
import sys
import time
from typing import Any, Callable, Iterable, Sequence

_LOGGER_FORMAT = (
    "%(asctime)s.%(msecs)03dZ %(levelname)s %(name)s:%(funcName)s:%(lineno)d "
    "%(message)s"
)
_LOGGER_DATEFMT = "%Y-%m-%dT%H:%M:%S"


def setup_logger(logger: logging.Logger, logfile: str | None = None,
                 verbose: bool = False) -> None:
    """Configure a logger with the framework's standard format."""
    logger.handlers.clear()
    handler: logging.Handler
    if logfile:
        handler = logging.FileHandler(logfile, encoding="utf-8")
    else:
        handler = logging.StreamHandler(sys.stderr)
    formatter = logging.Formatter(fmt=_LOGGER_FORMAT, datefmt=_LOGGER_DATEFMT)
    formatter.converter = time.gmtime
    handler.setFormatter(formatter)
    logger.addHandler(handler)
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logging.getLogger("batch_shipyard_tpu").handlers:
        setup_logger(logging.getLogger("batch_shipyard_tpu"))
    return logger


def atomic_write(path: str, data: bytes) -> None:
    """Crash-safe file replace: write to a uniquely-named sibling
    temp file, flush + fsync, then os.replace. THE durability idiom
    every ledger/journal/metadata writer in the framework shares
    (state store DBs, the agent's slot ledger, the resilient-store
    WAL) — a crash at any instant leaves either the old content or
    the new, never a torn file behind a committed rename."""
    tmp = f"{path}.tmp.{os.getpid()}.{random.getrandbits(32):08x}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def is_none_or_empty(value: Any) -> bool:
    return value is None or (hasattr(value, "__len__") and len(value) == 0)


def is_not_empty(value: Any) -> bool:
    return not is_none_or_empty(value)


def utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def datetime_utcnow_iso() -> str:
    return utcnow().strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def wrap_commands_in_shell(commands: Sequence[str], windows: bool = False,
                           wait: bool = True) -> str:
    """Wrap a list of shell commands into a single shell invocation string."""
    if windows:
        return 'cmd.exe /c "{}"'.format(" && ".join(commands))
    suffix = "; wait" if wait else ""
    return "/bin/bash -c 'set -e; set -o pipefail; {}{}'".format(
        "; ".join(commands), suffix)


def shell_quote(arg: str) -> str:
    return shlex.quote(arg)


def base64_encode_string(value: str) -> str:
    return base64.b64encode(value.encode("utf-8")).decode("ascii")


def base64_decode_string(value: str) -> str:
    return base64.b64decode(value).decode("utf-8")


def hash_string(value: str, algo: str = "sha256") -> str:
    return hashlib.new(algo, value.encode("utf-8")).hexdigest()


def hash_file(path: str, algo: str = "sha256") -> str:
    hasher = hashlib.new(algo)
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            hasher.update(chunk)
    return hasher.hexdigest()


def merge_dict(base: dict, overlay: dict) -> dict:
    """Recursively merge overlay into base, returning a new dict."""
    if not isinstance(base, dict) or not isinstance(overlay, dict):
        raise ValueError("merge_dict requires two dicts")
    result = dict(base)
    for key, value in overlay.items():
        if key in result and isinstance(result[key], dict) and isinstance(
                value, dict):
            result[key] = merge_dict(result[key], value)
        else:
            result[key] = value
    return result


def retry(fn: Callable[[], Any], attempts: int = 3,
          retryable: tuple[type[BaseException], ...] = (Exception,),
          initial_backoff: float = 0.25, max_backoff: float = 8.0,
          jitter: bool = True) -> Any:
    """Call fn with exponential backoff on retryable exceptions."""
    backoff = initial_backoff
    for attempt in range(attempts):
        try:
            return fn()
        except retryable:
            if attempt == attempts - 1:
                raise
            delay = backoff * (1 + random.random() if jitter else 1)
            time.sleep(min(delay, max_backoff))
            backoff = min(backoff * 2, max_backoff)


def subprocess_with_output(cmd: str | Sequence[str], shell: bool = False,
                           cwd: str | None = None,
                           env: dict[str, str] | None = None,
                           suppress_output: bool = False) -> int:
    """Run a subprocess, stream output, return exit code."""
    kwargs: dict[str, Any] = {}
    if suppress_output:
        kwargs["stdout"] = subprocess.DEVNULL
        kwargs["stderr"] = subprocess.DEVNULL
    proc = subprocess.Popen(cmd, shell=shell, cwd=cwd, env=env, **kwargs)
    return proc.wait()


def subprocess_capture(cmd: str | Sequence[str], shell: bool = False,
                       cwd: str | None = None,
                       env: dict[str, str] | None = None,
                       timeout: float | None = None,
                       stdin_data: str | None = None
                       ) -> tuple[int, str, str]:
    """Run a subprocess, capture stdout/stderr, return (rc, out, err).
    stdin_data feeds the child's stdin (secret values ride stdin, not
    argv, so they never appear in process listings)."""
    proc = subprocess.run(
        cmd, shell=shell, cwd=cwd, env=env, capture_output=True, text=True,
        timeout=timeout, input=stdin_data)
    return proc.returncode, proc.stdout, proc.stderr


def subprocess_nowait(cmd: str | Sequence[str], shell: bool = False,
                      cwd: str | None = None,
                      env: dict[str, str] | None = None,
                      stdout=None, stderr=None) -> subprocess.Popen:
    return subprocess.Popen(
        cmd, shell=shell, cwd=cwd, env=env, stdout=stdout, stderr=stderr)


def subprocess_wait_all(procs: Iterable[subprocess.Popen]) -> list[int]:
    return [proc.wait() for proc in procs]


def explode_cidr(cidr: str) -> tuple[str, int]:
    """Split a CIDR into (network address, prefix length)."""
    net = ipaddress.ip_network(cidr, strict=False)
    return str(net.network_address), net.prefixlen


def cidr_hosts(cidr: str) -> int:
    """Number of usable host addresses in a CIDR block."""
    net = ipaddress.ip_network(cidr, strict=False)
    return max(net.num_addresses - 2, 0) if net.prefixlen < 31 else (
        net.num_addresses)


def ip_in_cidr(ip: str, cidr: str) -> bool:
    return ipaddress.ip_address(ip) in ipaddress.ip_network(cidr, strict=False)


def confirm_action(msg: str, assume_yes: bool = False) -> bool:
    """Prompt the user for confirmation unless assume_yes."""
    if assume_yes:
        return True
    if not sys.stdin.isatty():
        return False
    answer = input(f"{msg} [y/n]: ").strip().lower()
    return answer in ("y", "yes")


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def chunked(seq: Sequence[Any], size: int) -> Iterable[Sequence[Any]]:
    for idx in range(0, len(seq), size):
        yield seq[idx:idx + size]


def human_bytes(num: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(num) < 1024.0:
            return f"{num:.1f}{unit}"
        num /= 1024.0
    return f"{num:.1f}PiB"


def probe_default_devices(timeout: float = 75.0
                          ) -> tuple[int, str | None]:
    """Count the default JAX backend's devices in a SUBPROCESS with a
    hard timeout, so a wedged accelerator relay can never hang the
    caller in-process (initializing a backend in-process is
    unrecoverable if it blocks). Returns (count, None) on success or
    (0, reason) on timeout/failure. Shared by bench.py's probe and
    __graft_entry__.dryrun_multichip's CPU-bootstrap decision."""
    import subprocess
    import sys as _sys

    try:
        proc = subprocess.run(
            [_sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return 0, (f"device init timed out after {timeout:.0f}s "
                   f"(wedged accelerator relay?)")
    if proc.returncode != 0:
        tail = proc.stderr.decode(errors="replace").strip()
        return 0, (f"device init exited rc={proc.returncode}: "
                   f"{tail[-400:]}")
    try:
        count = int(proc.stdout.decode().strip().splitlines()[-1])
    except (ValueError, IndexError):
        return 0, "device probe printed no device count"
    return count, None

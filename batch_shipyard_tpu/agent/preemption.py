"""Cooperative preemption: the process-side contract.

Priority within the queue bands is numeric (jobs/manager stamps
``spec["priority"]``), but a queue can only order WAITING work — when a
higher-priority task cannot place because lower-priority work holds
every slot, the scheduler must take slots back. Hard kills would work
(PR 5's chaos engine proves recovery survives them) but every hard
kill pays the full preemption-recovery badput leg: lost steps since
the last checkpoint plus a cold restart. Cooperative preemption
bounds that cost: the victim is asked to stop, drains to its next
step boundary, forces a COMMITTED checkpoint, and exits with a
distinct status — so the rerun resumes with ZERO lost steps beyond
the last barrier and the only badput is the requeue wait.

The delivery channel is the profile-request channel from the tracing
layer: the preempt sweep (agent/node_agent.py, leader-gated) stamps
``preempt_request`` on the victim task's entity; every agent's
heartbeat loop drops the request as a JSON file into its live tasks'
dirs (launch-path env: $SHIPYARD_PREEMPT_REQUEST_FILE); instrumented
workloads poll the file once per step (one os.stat while disarmed)
via PreemptWatcher — typically through
``checkpoint.TrainCheckpointer.maybe_preempt``.

Exit contract: a preempted task exits EXIT_PREEMPTED (75, EX_TEMPFAIL
— "temporary failure, retry"). The agent recognizes the code and
requeues at FULL retry budget with node health untouched: preemption
is a scheduling decision, never a task failure or a node's fault.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Env var the agent exports into every task: where a preempt request
# lands. With no sink configured the watcher is a no-op, so workloads
# run unchanged outside pools (the progress/goodput recorder rule).
PREEMPT_REQUEST_FILE_ENV = "SHIPYARD_PREEMPT_REQUEST_FILE"

# The distinct preempted exit status (EX_TEMPFAIL): the agent treats
# this code as "drained cooperatively — requeue at full budget", never
# as a failure. Chosen from sysexits so an uninstrumented shell task
# can participate with a plain `exit 75`.
EXIT_PREEMPTED = 75


def request_path() -> Optional[str]:
    """The preempt-request file for THIS process, or None."""
    return os.environ.get(PREEMPT_REQUEST_FILE_ENV) or None


def write_request(path: str, reason: str = "",
                  requested_at: Optional[str] = None,
                  **extra) -> None:
    """Drop one preempt request file (atomic: tmp + rename, so a
    watcher can never read a torn JSON). Used by the agent's delivery
    loop and the chaos node_preempt_notice injector."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"requested_at": requested_at
               or util.datetime_utcnow_iso(),
               "reason": reason, **extra}
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(payload))
    os.replace(tmp, path)


def read_request(path: str) -> Optional[dict]:
    """Parse a request file; None when absent or (transiently) torn."""
    try:
        with open(path, encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else {}


class PreemptWatcher:
    """Per-step preempt poll for workload loops.

    ``poll()`` costs one os.path.exists while disarmed; the first call
    that sees the request file parses, LATCHES, and returns it — later
    calls return None so a loop that keeps polling mid-drain cannot
    trigger a second drain. The file is left in place: the agent's
    per-(path, requested_at) dedup marker already prevents re-delivery
    after the harness consumed it, and keeping it makes the consumed
    request inspectable post-mortem."""

    def __init__(self, path: Optional[str] = None) -> None:
        self._path = path if path is not None else request_path()
        self._consumed = False

    @property
    def armed(self) -> bool:
        return self._path is not None and not self._consumed

    def poll(self) -> Optional[dict]:
        """The pending preempt request, exactly once, else None."""
        if self._path is None or self._consumed:
            return None
        if not os.path.exists(self._path):
            return None
        request = read_request(self._path)
        if request is None:
            # Torn write in flight (the writer is atomic, but a
            # foreign/manual drop may not be): retry next poll.
            return None
        self._consumed = True
        logger.warning("preempt request received (%s); draining to "
                       "the next step boundary",
                       request.get("reason") or "no reason given")
        return request


def preempt_requested() -> bool:
    """One-shot convenience for simple loops (no latch semantics)."""
    path = request_path()
    return bool(path and os.path.exists(path))


def wait_for_request(path: str, timeout: float,
                     poll_interval: float = 0.05) -> Optional[dict]:
    """Block until a request file appears (test/drill helper)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return read_request(path)
        time.sleep(poll_interval)
    return None

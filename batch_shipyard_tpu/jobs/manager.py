"""Job/task submission and monitoring.

Reference analog: convoy/batch.py add_jobs(:5056 — the 850-line loop) +
_construct_task(:4489) + _add_task_collection(:4313). Our submission
writes task entities + queue messages instead of Batch REST calls; the
node agents do the rest.

Task id generation follows the reference convention (task-%05d,
batch.py:4177) so depends_on_range works identically.
"""

from __future__ import annotations

import json
import math
import queue as queue_mod
import re
import threading
import time
import weakref
from typing import Iterator, Optional

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.config.settings import (
    JobSettings, PoolSettings, TaskSettings)
from batch_shipyard_tpu.jobs.task_factory import expand_task_factory
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class JobExistsError(RuntimeError):
    pass


class JobNotFoundError(RuntimeError):
    pass


def _task_spec(task: TaskSettings, job: JobSettings,
               pool: PoolSettings) -> dict:
    """Serializable task spec stored in the task entity and consumed by
    the node agent (the TaskAddParameter analog)."""
    spec = {
        "command": task.command,
        "runtime": task.runtime,
        "image": task.image,
        "environment_variables": dict(task.environment_variables),
        "tpu": task.tpu,
        "gpus": task.gpus,
        "depends_on": list(task.depends_on),
        "depends_on_range": (list(task.depends_on_range)
                             if task.depends_on_range else None),
        "max_task_retries": task.max_task_retries,
        "max_wall_time_seconds": task.max_wall_time_seconds,
        "progress_deadline_seconds": task.progress_deadline_seconds,
        "compile_cache_identity": task.compile_cache_identity,
        "retention_time_seconds": task.retention_time_seconds,
        "remove_container_after_exit": task.remove_container_after_exit,
        "shm_size": task.shm_size,
        "container_runtime": (pool.container_runtime_default
                              if pool is not None else "runc"),
        "additional_docker_run_options": list(
            task.additional_docker_run_options),
        "additional_singularity_options": list(
            task.additional_singularity_options),
        "input_data": list(task.input_data),
        "output_data": list(task.output_data),
        "resource_files": list(task.resource_files),
        "environment_variables_secret_id":
            job.environment_variables_secret_id,
        "allow_run_on_missing_image": job.allow_run_on_missing_image,
        "job_preparation_command": job.job_preparation_command,
        "job_input_data": list(job.input_data),
        "auto_scratch": job.auto_scratch,
        "exit_options": dict(task.default_exit_options),
        # Numeric priority: selects the queue band by sign (hi/lo
        # drain order, and retry requeues must land back on the same
        # band) and orders tasks WITHIN the band for the preempt
        # sweep — a pending task with a strictly higher number can
        # evict lower-priority running work.
        "priority": task.priority,
    }
    if task.multi_instance is not None:
        mi = task.multi_instance
        spec["multi_instance"] = {
            "num_instances": mi.resolve_num_instances(pool),
            "min_instances": mi.min_instances,
            "coordination_command": mi.coordination_command,
            "resource_files": list(mi.resource_files),
            "jax_distributed": {
                "enabled": mi.jax_distributed.enabled,
                "coordinator_port": mi.jax_distributed.coordinator_port,
                "transport": mi.jax_distributed.transport,
                "heartbeat_timeout_seconds":
                    mi.jax_distributed.heartbeat_timeout_seconds,
            },
            "pytorch_xla": {"enabled": mi.pytorch_xla},
        }
    return spec


def _expand_job_tasks(store: StateStore, job: JobSettings,
                      pool: PoolSettings,
                      required_node: Optional[str] = None,
                      start_number: int = 0,
                      ) -> list[tuple[str, dict]]:
    """Expand a job's task factories into (task_id, spec) pairs.
    Generic ids are numbered task-%05d from ``start_number``
    (reference id convention, batch.py:4177)."""
    task_number = start_number
    all_task_ids: list[str] = []
    pending: list[tuple[str, dict]] = []
    # Spec memoization: a repeat/sweep factory yields runs of equal
    # raw tasks, and settings-merge + spec construction dominate
    # large expansions (they are pure functions of the raw dict).
    # Re-deriving only when the raw task changes turns a 10^6-repeat
    # expansion from 10^6 merges into 1 — and the shared spec object
    # also collapses the submission's memory footprint. The spec must
    # then never be mutated per-task below (required_node is uniform
    # across the call), which also holds for every downstream reader.
    prev_raw: Optional[dict] = None
    prev_spec: Optional[dict] = None
    prev_explicit_id: Optional[str] = None
    for raw_task in job.tasks:
        for expanded in expand_task_factory(raw_task, store):
            if prev_raw is not None and (
                    expanded is prev_raw or expanded == prev_raw):
                spec = prev_spec
                task_id = prev_explicit_id or \
                    f"task-{task_number:05d}"
            else:
                task = settings_mod.task_settings(expanded, job, pool)
                spec = _task_spec(task, job, pool)
                if required_node:
                    spec["required_node"] = required_node
                prev_raw = expanded if expanded is not raw_task \
                    else dict(expanded)
                prev_spec = spec
                prev_explicit_id = task.id
                task_id = task.id or f"task-{task_number:05d}"
            task_number += 1
            pending.append((task_id, spec))
            all_task_ids.append(task_id)
    if job.merge_task is not None:
        # Merge task: runs after every other task of the job
        # (reference batch.py merge_task handling :4177-4242).
        merge_raw = dict(job.merge_task)
        merge_raw["depends_on"] = all_task_ids
        task = settings_mod.task_settings(merge_raw, job, pool)
        merge_id = task.id or "merge-task"
        spec = _task_spec(task, job, pool)
        if required_node:
            spec["required_node"] = required_node
        pending.append((merge_id, spec))
    return pending


def add_jobs(store: StateStore, pool: PoolSettings,
             jobs: list[JobSettings],
             pool_id_override: Optional[str] = None,
             required_node: Optional[str] = None) -> dict[str, int]:
    """Submit jobs + tasks; returns {job_id: task_count}.

    ``required_node`` pins every task to one node (federation
    required-target select): agents bounce non-matching deliveries.
    """
    submitted: dict[str, int] = {}
    for job in jobs:
        pool_id = pool_id_override or job.pool_id or pool.id
        # The distributed trace is born HERE: one trace per job
        # submission, whose root is the submit span. Every task row
        # carries the trace id + its own root span id, so the whole
        # chain (queue wait, claim, rendezvous, program phases) is
        # attributable to this `jobs add`.
        trace = trace_ctx.TraceContext.new()
        submit_started = time.time()
        try:
            # One insert-as-claim per JOB (EntityExistsError below is
            # the duplicate-submission guard); jobs-per-call is O(1),
            # the per-task fan-out under it is fully batched.
            store.insert_entity(names.TABLE_JOBS, pool_id, job.id, {  # shipyard-lint: disable=store-write-in-loop
                "state": "active",
                trace_ctx.COL_TRACE_ID: trace.trace_id,
                trace_ctx.COL_TRACE_SPAN: trace.span_id,
                "spec": {
                    "auto_complete": job.auto_complete,
                    "priority": job.priority,
                    "job_release_command": job.job_release_command,
                    "auto_scratch": job.auto_scratch,
                    "recurrence": (
                        {"interval":
                         job.recurrence.recurrence_interval_seconds}
                        if job.recurrence else None),
                },
                "created_at": util.datetime_utcnow_iso(),
            })
        except EntityExistsError:
            raise JobExistsError(f"job {job.id} exists on pool {pool_id}")
        if job.server_side_expansion:
            # O(1) client leg: park the generator spec as ONE
            # expansion row; the pool's leader-gated expander
            # (jobs/expansion.py) materializes rows + messages.
            from batch_shipyard_tpu.jobs import expansion as \
                expansion_mod
            expansion_mod.submit_expansion(
                store, pool_id, job, trace=trace,
                required_node=required_node)
            trace_spans.emit(
                store, pool_id, trace_spans.SPAN_SUBMIT, trace,
                job_id=job.id, start=submit_started, end=time.time(),
                attrs={"tasks": 0, "server_side_expansion": True},
                self_span=True)
            logger.info(
                "job %s submitted for server-side expansion under "
                "trace %s", job.id, trace.trace_id)
            submitted[job.id] = 0
            continue
        pending = _expand_job_tasks(store, job, pool,
                                    required_node=required_node)
        _submit_tasks_batched(store, pool_id, job.id, pending,
                              priority=job.priority, trace=trace)
        # The submit span covers entity+message fan-out; recorded
        # LAST so its end time is honest. Its own span_id is the
        # trace root (parent of every task's root span).
        trace_spans.emit(
            store, pool_id, trace_spans.SPAN_SUBMIT, trace,
            job_id=job.id, start=submit_started, end=time.time(),
            attrs={"tasks": len(pending)}, self_span=True)
        logger.info("job %s submitted under trace %s", job.id,
                    trace.trace_id)
        submitted[job.id] = len(pending)
    return submitted


_GENERIC_TASK_ID = re.compile(r"^task-(\d{5,})$")


def merge_tasks_into_job(store: StateStore, pool: PoolSettings,
                         job: JobSettings, pool_id: str,
                         required_node: Optional[str] = None) -> int:
    """Add a job spec's tasks to an ALREADY EXISTING job, remapping
    colliding task ids.

    Reference analog: federation schedule_tasks task-id fixup
    (federation/federation.py:2605 fixup + :2699
    regenerate_next_generic_task_id) — a federated action targeting a
    job that already ran on the pool re-numbers generic ids past the
    job's current maximum so the merge never collides; depends_on
    references within the incoming batch are remapped consistently.
    Explicit (non-generic) ids that collide are an error. Returns the
    number of tasks added.
    """
    job_entity = get_job(store, pool_id, job.id)  # must exist
    # Merged tasks join the job's EXISTING trace (their root spans
    # parent under the original submit span); None for legacy jobs.
    trace = trace_ctx.TraceContext.from_entity(job_entity)
    existing = {t["_rk"] for t in list_tasks(store, pool_id, job.id)}
    next_number = 0
    for tid in existing:
        match = _GENERIC_TASK_ID.match(tid)
        if match:
            next_number = max(next_number, int(match.group(1)) + 1)
    # Expand under the batch's OWN numbering (task-00000...), so
    # depends_on references within the incoming batch resolve to
    # batch members; collisions with existing ids are then renumbered
    # past the job's current maximum and the references remapped.
    pending = _expand_job_tasks(store, job, pool,
                                required_node=required_node)
    remap: dict[str, str] = {}
    out: list[tuple[str, dict]] = []
    has_range_deps = any(spec.get("depends_on_range")
                         for _, spec in pending)
    # Renumbered ids must dodge existing ids, ids already assigned in
    # this merge, AND not-yet-processed ids of the incoming batch —
    # otherwise renaming task-00000 to task-00005 collides with an
    # incoming task-00005 later in the same batch.
    taken = set(existing) | {tid for tid, _ in pending}
    for task_id, spec in pending:
        new_id = task_id
        if task_id in existing:
            if has_range_deps:
                # depends_on_range references numeric ids positionally;
                # re-numbering would silently retarget them (the
                # reference likewise skips re-id when dependencies are
                # present, federation.py:2686).
                raise JobExistsError(
                    f"cannot merge tasks into job {job.id}: id "
                    f"{task_id} collides and the batch uses "
                    f"depends_on_range")
            if _GENERIC_TASK_ID.match(task_id) or task_id == "merge-task":
                while f"task-{next_number:05d}" in taken:
                    next_number += 1
                new_id = f"task-{next_number:05d}"
                next_number += 1
            else:
                raise JobExistsError(
                    f"task {task_id} already exists in job {job.id} "
                    f"on pool {pool_id} and is not a generic id")
        taken.add(new_id)
        remap[task_id] = new_id
        out.append((new_id, spec))
    for _, spec in out:
        spec["depends_on"] = [remap.get(d, d)
                              for d in spec.get("depends_on", [])]
    _submit_tasks_batched(store, pool_id, job.id, out,
                          priority=job.priority, trace=trace)
    return len(out)


# Adaptive submission chunking: start at the reference's 100-task
# TaskAddCollection size and grow while a chunk's store-commit time
# stays under the target — large batches amortize round trips, but an
# unbounded chunk would turn one slow backend call into a visibility
# cliff (and a giant all-or-nothing batch on the atomic backends).
_SUBMIT_CHUNK_MIN = 100
_SUBMIT_CHUNK_MAX = 10_000
_SUBMIT_CHUNK_TARGET_SECONDS = 0.25

# Queue-shard autoscale: grow the pool's task_queue_shards while the
# observed submission rate exceeds what the current shard set should
# carry. Grow-only — the old shard names are a strict subset of the
# new set (names.task_queue), so in-flight messages stay claimable
# and producers/consumers may disagree about the count transiently
# without stranding a queue.
_SHARD_TASKS_PER_SECOND = 2500.0
_MAX_AUTOSCALE_SHARDS = 32

# pool_queue_shards cache: per-(store, pool), TTL-bounded. Bulk
# submission used to pay one pool-entity read per chunk for a value
# that changes only on resize/autoscale; the WeakKey keeps a store's
# cache from outliving the store (tests build thousands).
_SHARDS_CACHE_TTL = 15.0
_shards_cache: "weakref.WeakKeyDictionary[StateStore, dict]" = \
    weakref.WeakKeyDictionary()
_shards_cache_lock = threading.Lock()


def pool_queue_shards(store: StateStore, pool_id: str,
                      ttl: Optional[float] = _SHARDS_CACHE_TTL) -> int:
    """Task-queue shard count for a pool, read from its stored spec
    (so cross-pool producers — federation, migrate — route to the
    TARGET pool's sharding, not the caller's). Cached per
    (store, pool) for ``ttl`` seconds; pass ``ttl=0`` to force a
    fresh read. Resize/autoscale invalidate the writer's own cache
    eagerly (invalidate_pool_queue_shards); other processes converge
    within the TTL, which grow-only sharding makes safe."""
    now = time.monotonic()
    if ttl:
        with _shards_cache_lock:
            hit = _shards_cache.get(store, {}).get(pool_id)
            if hit is not None and now - hit[1] < ttl:
                return hit[0]
    try:
        pool = store.get_entity(names.TABLE_POOLS, "pools", pool_id)
        shards = int(pool.get("spec", {})
                     .get("pool_specification", {})
                     .get("task_queue_shards", 1))
    except NotFoundError:
        return 1  # transient (pool mid-create): never cache it
    with _shards_cache_lock:
        try:
            _shards_cache.setdefault(store, {})[pool_id] = (shards,
                                                            now)
        except TypeError:
            pass  # un-weakref-able store stand-in: skip caching
    return shards


def invalidate_pool_queue_shards(store: Optional[StateStore] = None,
                                 pool_id: Optional[str] = None
                                 ) -> None:
    """Drop cached shard counts — for one (store, pool), one store,
    or everything. Called by pool resize and the submission-rate
    autoscale so the writer's next routing decision sees its own
    update immediately."""
    with _shards_cache_lock:
        if store is None:
            for per_store in _shards_cache.values():
                if pool_id is None:
                    per_store.clear()
                else:
                    per_store.pop(pool_id, None)
        elif pool_id is None:
            _shards_cache.pop(store, None)
        else:
            _shards_cache.get(store, {}).pop(pool_id, None)


def maybe_autoscale_queue_shards(store: StateStore, pool_id: str,
                                 tasks_per_second: float) -> int:
    """Grow ``task_queue_shards`` to match an observed submission
    rate (the tentpole's autoscale hook: called by the streaming
    submitter and the server-side expander once they can measure
    their own throughput). Returns the effective shard count.
    Grow-only and etag-guarded; a lost race just means the other
    writer's (also grow-only) value stands."""
    desired = min(_MAX_AUTOSCALE_SHARDS,
                  max(1, math.ceil(tasks_per_second
                                   / _SHARD_TASKS_PER_SECOND)))
    current = pool_queue_shards(store, pool_id, ttl=0)
    if desired <= current:
        return current
    try:
        pool = store.get_entity(names.TABLE_POOLS, "pools", pool_id)
        spec = dict(pool.get("spec", {}))
        pool_spec = dict(spec.get("pool_specification", {}))
        if int(pool_spec.get("task_queue_shards", 1)) >= desired:
            return int(pool_spec["task_queue_shards"])
        pool_spec["task_queue_shards"] = desired
        spec["pool_specification"] = pool_spec
        store.merge_entity(names.TABLE_POOLS, "pools", pool_id,
                           {"spec": spec}, if_match=pool["_etag"])
    except (NotFoundError, EtagMismatchError):
        return current
    invalidate_pool_queue_shards(store, pool_id)
    logger.info("task queue shards for pool %s grown %d -> %d "
                "(observed %.0f tasks/s)", pool_id, current, desired,
                tasks_per_second)
    return desired


def _encode_chunk_messages(pool_id: str, job_id: str,
                           chunk: list[tuple[str, dict]],
                           shards: int, priority: int,
                           trace: Optional[trace_ctx.TraceContext],
                           ) -> dict[str, list[bytes]]:
    """Encode one chunk's queue payloads, amortizing the JSON work:
    the shared head/tail of every message is serialized once and the
    per-task/per-instance remainder is string-assembled — emitting
    bytes identical to a per-message json.dumps of
    {"job_id", "task_id"[, "trace_id"][, "instance"]} in that key
    order (the equivalence property test pins this)."""
    head = '{"job_id": ' + json.dumps(job_id) + ', "task_id": '
    tail = (', "trace_id": ' + json.dumps(trace.trace_id)
            if trace is not None else '')
    by_queue: dict[str, list[bytes]] = {}
    for task_id, spec in chunk:
        # Per-task numeric priority routes the band (a task may
        # override its job's priority); the job-level param is the
        # legacy fallback for specs without one.
        queue = names.task_queue_for(
            pool_id, task_id, shards,
            priority=int(spec.get("priority", priority) or 0))
        base = head + json.dumps(task_id) + tail
        num_instances = (spec.get("multi_instance") or {}).get(
            "num_instances")
        if num_instances:
            # Gang fan-out is part of the batched encode: one shared
            # body + the instance index, not one json.dumps per
            # instance.
            by_queue.setdefault(queue, []).extend(
                (base + ', "instance": ' + str(k) + '}').encode()
                for k in range(num_instances))
        else:
            by_queue.setdefault(queue, []).append(
                (base + '}').encode())
    return by_queue


def _insert_rows_tolerant(store: StateStore, rows: list[tuple]) -> None:
    """Batch insert that treats EntityExistsError as already-applied
    (the WAL replay discipline): the server-side expander's resume
    path re-submits the chunk its predecessor may have half-landed,
    and re-inserted rows must converge instead of erroring."""
    try:
        store.insert_entities(names.TABLE_TASKS, rows)
    except EntityExistsError:
        for pk, rk, entity in rows:
            try:
                store.insert_entity(names.TABLE_TASKS, pk, rk,  # shipyard-lint: disable=store-write-in-loop
                                    entity)
            except EntityExistsError:
                pass


def _submit_tasks_batched(store: StateStore, pool_id: str, job_id: str,
                          tasks: list[tuple[str, dict]],
                          priority: int = 0,
                          trace: Optional[
                              trace_ctx.TraceContext] = None,
                          stats: Optional[dict] = None,
                          tolerate_existing: bool = False) -> None:
    """Streaming pipelined batch submission (supersedes the fixed
    100-task chunks of the reference's TaskAddCollection,
    batch.py:4313). Three overlapped legs connected by bounded
    queues:

        encode (caller thread) -> entity insert -> queue enqueue

    so while chunk N's rows commit, chunk N+1 encodes and chunk N-1's
    messages enqueue — a chunk's messages still strictly FOLLOW its
    rows (an agent must never claim a message whose task row is not
    yet readable). Chunk size adapts to the measured store-commit
    time (slow start from _SUBMIT_CHUNK_MIN toward the target
    seconds), and the shard autoscale hook runs once a rate is
    observable.

    ``priority`` selects the queue band agents drain first. ``trace``
    stamps each row with the trace id + its own root span and each
    message with the trace id. ``stats`` (optional dict) accumulates
    the submit-leg breakdown: encode/entity/enqueue seconds and task/
    message counts. ``tolerate_existing`` re-applies rows
    idempotently (expander resume)."""
    if not tasks:
        return
    pk = names.task_pk(pool_id, job_id)
    shards = pool_queue_shards(store, pool_id)
    submitted_at = util.datetime_utcnow_iso()
    out: dict = {"encode_seconds": 0.0, "entity_seconds": 0.0,
                 "enqueue_seconds": 0.0, "tasks": 0, "messages": 0,
                 "chunks": 0, "shards": shards}
    insert_rows = _insert_rows_tolerant if tolerate_existing else (
        lambda s, rows: s.insert_entities(names.TABLE_TASKS, rows))

    if len(tasks) <= _SUBMIT_CHUNK_MIN:
        # Inline path: one chunk needs no pipeline (and retry
        # requeues / unit submissions shouldn't pay two thread
        # spawns per task).
        t0 = time.monotonic()
        rows = []
        for task_id, spec in tasks:
            entity = {"state": "pending", "spec": spec, "retries": 0,
                      "submitted_at": submitted_at}
            if trace is not None:
                entity.update(trace.child().entity_columns())
            rows.append((pk, task_id, entity))
        by_queue = _encode_chunk_messages(pool_id, job_id, tasks,
                                          shards, priority, trace)
        out["encode_seconds"] = time.monotonic() - t0
        t0 = time.monotonic()
        insert_rows(store, rows)
        out["entity_seconds"] = time.monotonic() - t0
        t0 = time.monotonic()
        for queue_name, payloads in by_queue.items():
            store.put_messages(queue_name, payloads)
            out["messages"] += len(payloads)
        out["enqueue_seconds"] = time.monotonic() - t0
        out["tasks"] = len(tasks)
        out["chunks"] = 1
        if stats is not None:
            for key, value in out.items():
                if isinstance(value, (int, float)) and key != "shards":
                    stats[key] = stats.get(key, 0) + value
            stats["shards"] = out["shards"]
        return

    # Bounded handoffs: depth 2 keeps all three legs busy without
    # letting a fast encoder pile unbounded row batches in memory.
    entity_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
    enqueue_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
    errors: list[BaseException] = []
    # Feedback from the store legs to the (caller-thread) encoder:
    # the slowest observed store-commit time for the last chunk size
    # drives the adaptation.
    feedback = {"commit_seconds": 0.0, "rows": 0}
    feedback_lock = threading.Lock()

    def entity_leg() -> None:
        try:
            while True:
                item = entity_q.get()
                if item is None:
                    enqueue_q.put(None)
                    return
                rows, by_queue = item
                t0 = time.monotonic()
                insert_rows(store, rows)
                dt = time.monotonic() - t0
                out["entity_seconds"] += dt
                with feedback_lock:
                    feedback["commit_seconds"] = dt
                    feedback["rows"] = len(rows)
                enqueue_q.put((len(rows), by_queue))
        except BaseException as exc:  # noqa: BLE001 - rethrown below
            errors.append(exc)
            enqueue_q.put(None)
            # Drain so the producer's bounded put never deadlocks.
            while entity_q.get() is not None:
                pass

    def enqueue_leg() -> None:
        try:
            while True:
                item = enqueue_q.get()
                if item is None:
                    return
                nrows, by_queue = item
                t0 = time.monotonic()
                for queue_name, payloads in by_queue.items():
                    store.put_messages(queue_name, payloads)
                    out["messages"] += len(payloads)
                out["enqueue_seconds"] += time.monotonic() - t0
                out["tasks"] += nrows
                out["chunks"] += 1
        except BaseException as exc:  # noqa: BLE001 - rethrown below
            errors.append(exc)
            while enqueue_q.get() is not None:
                pass

    threads = [threading.Thread(target=entity_leg,
                                name="submit-entities", daemon=True),
               threading.Thread(target=enqueue_leg,
                                name="submit-enqueue", daemon=True)]
    for t in threads:
        t.start()
    chunk_size = _SUBMIT_CHUNK_MIN
    started = time.monotonic()
    autoscaled = False
    position = 0
    try:
        while position < len(tasks) and not errors:
            chunk = tasks[position:position + chunk_size]
            position += len(chunk)
            t0 = time.monotonic()
            rows = []
            if trace is not None:
                for task_id, spec in chunk:
                    entity = {"state": "pending", "spec": spec,
                              "retries": 0,
                              "submitted_at": submitted_at}
                    entity.update(trace.child().entity_columns())
                    rows.append((pk, task_id, entity))
            else:
                rows = [(pk, task_id,
                         {"state": "pending", "spec": spec,
                          "retries": 0, "submitted_at": submitted_at})
                        for task_id, spec in chunk]
            by_queue = _encode_chunk_messages(
                pool_id, job_id, chunk, shards, priority, trace)
            out["encode_seconds"] += time.monotonic() - t0
            entity_q.put((rows, by_queue))
            # Adapt: grow while the store leg commits chunks faster
            # than the target, shrink when a chunk blew past it.
            with feedback_lock:
                commit, nrows = (feedback["commit_seconds"],
                                 feedback["rows"])
            if nrows:
                if commit < _SUBMIT_CHUNK_TARGET_SECONDS / 2:
                    chunk_size = min(_SUBMIT_CHUNK_MAX,
                                     chunk_size * 2)
                elif commit > _SUBMIT_CHUNK_TARGET_SECONDS * 2:
                    chunk_size = max(_SUBMIT_CHUNK_MIN,
                                     chunk_size // 2)
            if not autoscaled:
                elapsed = time.monotonic() - started
                if elapsed >= 1.0 and position < len(tasks):
                    autoscaled = True
                    rate = position / elapsed
                    new_shards = maybe_autoscale_queue_shards(
                        store, pool_id, rate)
                    if new_shards > shards:
                        # Grow-only: chunks already routed with the
                        # old count stay claimable (subset property).
                        shards = new_shards
                        out["shards"] = shards
    finally:
        entity_q.put(None)
        for t in threads:
            t.join()
    if stats is not None:
        for key, value in out.items():
            if isinstance(value, (int, float)) and key != "shards":
                stats[key] = stats.get(key, 0) + value
        stats["shards"] = out["shards"]
    if errors:
        raise errors[0]


def _submit_task(store: StateStore, pool_id: str, job_id: str,
                 task_id: str, spec: dict) -> None:
    _submit_tasks_batched(store, pool_id, job_id, [(task_id, spec)])


def list_jobs(store: StateStore, pool_id: str) -> list[dict]:
    return list(store.query_entities(names.TABLE_JOBS,
                                     partition_key=pool_id))


def get_job(store: StateStore, pool_id: str, job_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_JOBS, pool_id, job_id)
    except NotFoundError:
        raise JobNotFoundError(job_id)


def list_tasks(store: StateStore, pool_id: str,
               job_id: str) -> list[dict]:
    return list(store.query_entities(
        names.TABLE_TASKS, partition_key=names.task_pk(pool_id, job_id)))


def get_task(store: StateStore, pool_id: str, job_id: str,
             task_id: str) -> dict:
    try:
        return store.get_entity(
            names.TABLE_TASKS, names.task_pk(pool_id, job_id), task_id)
    except NotFoundError:
        raise JobNotFoundError(f"{job_id}/{task_id}")


def job_task_summary(store: StateStore, pool_id: str,
                     job_id: str) -> dict:
    """Terminal-state summary of one job via the server-side group
    count (count_entities_by): {"total", "terminal", "by_state"} —
    one aggregate read instead of listing every task row. At 10^6
    tasks this is what makes a wait poll loop usable."""
    counts = store.count_entities_by(
        names.TABLE_TASKS, names.task_pk(pool_id, job_id))
    total = sum(counts.values())
    terminal = sum(counts.get(state, 0)
                   for state in names.TERMINAL_TASK_STATES)
    return {"total": total, "terminal": terminal, "by_state": counts}


def wait_for_job_summary(store: StateStore, pool_id: str, job_id: str,
                         timeout: float = 600.0,
                         poll_interval: float = 0.2,
                         on_progress=None) -> dict:
    """Block until every task of a job is terminal, polling the O(1)
    summary (never the task list). A pending server-side expansion
    gates completion: until the expander reports the job fully
    materialized, an all-terminal count only covers the prefix it has
    landed so far. Returns the final summary; ``on_progress`` (if
    given) is called with each interim summary."""
    from batch_shipyard_tpu.jobs import expansion as expansion_mod
    deadline = time.monotonic() + timeout
    while True:
        summary = job_task_summary(store, pool_id, job_id)
        expansion = expansion_mod.expansion_state(store, pool_id,
                                                 job_id)
        if expansion == "failed":
            raise RuntimeError(
                f"server-side expansion of {job_id} failed: "
                f"{expansion_mod.expansion_error(store, pool_id, job_id)}")
        expanded = expansion is None or expansion == "completed"
        if expanded and summary["total"] and \
                summary["terminal"] == summary["total"]:
            return summary
        if on_progress is not None:
            on_progress(summary)
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"tasks of {job_id} not terminal after {timeout}s: "
                f"{summary['by_state']}"
                + ("" if expanded else
                   f" (expansion {expansion})"))
        time.sleep(poll_interval)


def wait_for_tasks(store: StateStore, pool_id: str, job_id: str,
                   timeout: float = 600.0,
                   poll_interval: float = 0.2) -> list[dict]:
    """Block until all tasks of a job are terminal; returns them.
    Polls the counting summary (one aggregate read per tick) and
    lists the full task set exactly once, at the end."""
    wait_for_job_summary(store, pool_id, job_id, timeout=timeout,
                         poll_interval=poll_interval)
    return list_tasks(store, pool_id, job_id)


def get_task_output(store: StateStore, pool_id: str, job_id: str,
                    task_id: str, filename: str = "stdout.txt",
                    instance: Optional[int] = None) -> bytes:
    name = (f"i{instance}/{filename}" if instance is not None
            else filename)
    key = names.task_output_key(pool_id, job_id, task_id, name)
    return store.get_object(key)


def stream_task_output(store: StateStore, pool_id: str, job_id: str,
                       task_id: str, filename: str = "stdout.txt",
                       timeout: float = 600.0,
                       poll_interval: float = 0.5) -> Iterator[bytes]:
    """Poll-follow a task's output until the task is terminal
    (stream_file_and_wait_for_task analog, batch.py:3243)."""
    offset = 0
    deadline = time.monotonic() + timeout
    key = names.task_output_key(pool_id, job_id, task_id, filename)
    while True:
        task = get_task(store, pool_id, job_id, task_id)
        try:
            data = store.get_object(key)
            if len(data) > offset:
                yield data[offset:]
                offset = len(data)
        except NotFoundError:
            pass
        if task.get("state") in names.TERMINAL_TASK_STATES:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(f"stream of {task_id} timed out")
        time.sleep(poll_interval)


def terminate_job(store: StateStore, pool_id: str, job_id: str,
                  wait: bool = False) -> None:
    """Terminate: mark job + non-terminal tasks; fan out job-release
    (jobs term analog, batch.py:2770 terminate_tasks +
    del_or_term_jobs)."""
    job = get_job(store, pool_id, job_id)
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "terminated",
                        "completed_at": util.datetime_utcnow_iso()})
    pk = names.task_pk(pool_id, job_id)
    for task in list_tasks(store, pool_id, job_id):
        if task.get("state") not in names.TERMINAL_TASK_STATES:
            try:
                store.merge_entity(
                    names.TABLE_TASKS, pk, task["_rk"],
                    {"state": "failed", "exit_code": -9,
                     "error": "job terminated"},
                    if_match=task["_etag"])
            except Exception:
                pass
    for row in store.query_entities(names.TABLE_JOBPREP,
                                    partition_key=pk):
        # One message per DISTINCT per-node control queue — there is
        # no batch to combine across queues.
        store.put_message(  # shipyard-lint: disable=store-write-in-loop
            names.control_queue(pool_id, row["_rk"]),
            json.dumps({"type": "job_release",
                        "job_id": job_id}).encode())


def disable_job(store: StateStore, pool_id: str, job_id: str) -> None:
    """Disable: pending tasks stay queued but agents will not start
    them until re-enabled (jobs disable --requeue analog,
    batch.py:2102). Only active jobs can be disabled — a terminated/
    completed job must not be resurrectable via disable+enable."""
    job = get_job(store, pool_id, job_id)
    if job.get("state") != "active":
        raise ValueError(
            f"job {job_id} is {job.get('state')}; only active jobs "
            f"can be disabled")
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "disabled"}, if_match=job["_etag"])


def enable_job(store: StateStore, pool_id: str, job_id: str) -> None:
    job = get_job(store, pool_id, job_id)
    if job.get("state") != "disabled":
        raise ValueError(f"job {job_id} is not disabled")
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "active"})


def migrate_job(store: StateStore, src_pool_id: str, job_id: str,
                dst_pool_id: str) -> int:
    """Live job migration between pools: move the job entity and
    re-enqueue all non-terminal tasks on the destination pool's queue
    (jobs migrate analog, batch.py:1855 check_pool_for_job_migration +
    :1911 update_job_with_pool). Returns moved task count."""
    job = get_job(store, src_pool_id, job_id)
    try:
        get_job(store, dst_pool_id, job_id)
        raise JobExistsError(
            f"job {job_id} already exists on pool {dst_pool_id}")
    except JobNotFoundError:
        pass
    try:
        store.get_entity(names.TABLE_POOLS, "pools", dst_pool_id)
    except NotFoundError:
        raise ValueError(
            f"destination pool {dst_pool_id} does not exist")
    src_pk = names.task_pk(src_pool_id, job_id)
    dst_pk = names.task_pk(dst_pool_id, job_id)
    # Validate BEFORE any mutation: a half-migrated job is
    # unrecoverable without manual store surgery. Requiring the job to
    # be disabled (not merely no-running-tasks) closes the race where
    # a source-pool agent claims a pending task mid-migration.
    if job.get("state") == "active":
        raise RuntimeError(
            f"job {job_id} is active; run jobs disable first, wait "
            f"for running tasks to drain, then migrate")
    tasks = list(store.query_entities(names.TABLE_TASKS,
                                      partition_key=src_pk))
    running = [t["_rk"] for t in tasks
               if t.get("state") in ("assigned", "running")]
    if running:
        raise RuntimeError(
            f"tasks {running} are still running; wait for them to "
            f"drain before migrating")
    moved = 0
    store.insert_entity(names.TABLE_JOBS, dst_pool_id, job_id, {
        "state": job.get("state", "active"), "spec": job.get("spec", {}),
        "created_at": job.get("created_at"),
        "migrated_from": src_pool_id,
    })
    dst_shards = pool_queue_shards(store, dst_pool_id, ttl=0)
    job_priority = int(job.get("spec", {}).get("priority", 0) or 0)
    # Batched commit (the store-write-in-loop showcase fix): build
    # every destination row and message first, then land them as
    # batches — rows strictly before messages, so a destination
    # agent can never claim a message whose task row is unreadable.
    # Source-row deletes follow last: a crash mid-migrate leaves
    # duplicate claim-proof rows (job stays disabled), never a task
    # that exists nowhere.
    rows: list[tuple[str, str, dict]] = []
    by_queue: dict[str, list[bytes]] = {}
    for task in tasks:
        entity = {k: v for k, v in task.items()
                  if not k.startswith("_")}
        rows.append((dst_pk, task["_rk"], entity))
        if entity.get("state") in names.CLAIMABLE_TASK_STATES:
            # Per-task priority routes the band, same rule as
            # submission — a hi-band task must not lose its drain
            # precedence by migrating.
            dst_queue = names.task_queue_for(
                dst_pool_id, task["_rk"], dst_shards,
                priority=int((entity.get("spec") or {}).get(
                    "priority", job_priority) or 0))
            message = {"job_id": job_id, "task_id": task["_rk"]}
            if entity.get(trace_ctx.COL_TRACE_ID):
                message["trace_id"] = entity[trace_ctx.COL_TRACE_ID]
            num_instances = (entity.get("spec", {}).get(
                "multi_instance") or {}).get("num_instances")
            if num_instances:
                # Elastic override: a resized gang migrates at its
                # CURRENT effective size — fanning out the spec size
                # onto the destination would wedge the rendezvous the
                # same way it would have on the source.
                effective = int(
                    entity.get(names.TASK_COL_GANG_SIZE)
                    or num_instances)
                by_queue.setdefault(dst_queue, []).extend(
                    json.dumps({**message, "instance": k}).encode()
                    for k in range(effective))
            else:
                by_queue.setdefault(dst_queue, []).append(
                    json.dumps(message).encode())
            moved += 1
        if (entity.get("spec", {}).get("multi_instance")
                or {}).get("num_instances"):
            # Source-pool rendezvous rows would otherwise orphan:
            # gang partitions are POOL-scoped, so the destination's
            # janitor can never sweep them, and the source pool may
            # have no live agents left to (the migration trigger).
            attempts = (int(entity.get("retries", 0) or 0)
                        + int(entity.get(
                            names.TASK_COL_PREEMPT_COUNT, 0) or 0)
                        + int(entity.get(
                            names.TASK_COL_EVICT_COUNT, 0) or 0))
            for attempt in range(attempts + 1):
                gang_pk = names.gang_pk(src_pool_id, job_id,
                                        task["_rk"], attempt=attempt)
                for gang_row in list(store.query_entities(
                        names.TABLE_GANGS, partition_key=gang_pk)):
                    try:
                        store.delete_entity(names.TABLE_GANGS,
                                            gang_pk,
                                            gang_row["_rk"])
                    except NotFoundError:
                        pass
    for start in range(0, len(rows), _SUBMIT_CHUNK_MIN):
        store.insert_entities(names.TABLE_TASKS,
                              rows[start:start + _SUBMIT_CHUNK_MIN])
    for dst_queue, payloads in by_queue.items():
        store.put_messages(dst_queue, payloads)
    for task in tasks:
        store.delete_entity(names.TABLE_TASKS, src_pk, task["_rk"])
    store.delete_entity(names.TABLE_JOBS, src_pool_id, job_id)
    return moved


def cleanup_mi_containers(store: StateStore, pool_id: str) -> int:
    """Fan out orphaned multi-instance container cleanup to every node
    (jobs cmi analog, batch.py:2322). Returns node count."""
    count = 0
    for node in store.query_entities(names.TABLE_NODES,
                                     partition_key=pool_id):
        # One message per DISTINCT per-node control queue — no batch
        # exists across queues.
        store.put_message(  # shipyard-lint: disable=store-write-in-loop
            names.control_queue(pool_id, node["_rk"]),
            json.dumps({"type": "cleanup_mi"}).encode())
        count += 1
    return count


def terminate_task(store: StateStore, pool_id: str, job_id: str,
                   task_id: str, wait: bool = False,
                   timeout: float = 60.0) -> None:
    """Terminate one task (tasks term analog, batch.py:2770): pending
    tasks are marked failed; running tasks get a kill relayed to their
    node's agent."""
    task = get_task(store, pool_id, job_id, task_id)
    state = task.get("state")
    if state in names.TERMINAL_TASK_STATES:
        return
    if state in names.CLAIMABLE_TASK_STATES:
        # pending OR preempted-awaiting-reclaim: nothing is running,
        # mark terminal directly.
        try:
            store.merge_entity(
                names.TABLE_TASKS, names.task_pk(pool_id, job_id),
                task_id, {"state": "failed", "exit_code": -9,
                          "error": "terminated by user"},
                if_match=task["_etag"])
            return
        except EtagMismatchError:
            task = get_task(store, pool_id, job_id, task_id)
    node_id = task.get("node_id")
    if node_id:
        store.put_message(
            names.control_queue(pool_id, node_id),
            json.dumps({"type": "term_task", "job_id": job_id,
                        "task_id": task_id}).encode())
    if wait:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            task = get_task(store, pool_id, job_id, task_id)
            if task.get("state") in names.TERMINAL_TASK_STATES:
                return
            time.sleep(0.2)
        raise TimeoutError(f"task {task_id} did not terminate")


def request_preemption(store: StateStore, pool_id: str, job_id: str,
                       task_id: str, reason: str = "",
                       by_job_id: Optional[str] = None,
                       by_task_id: Optional[str] = None,
                       leader_epoch: Optional[int] = None,
                       defer_notice: bool = False):
    """Stamp a cooperative preempt request on a RUNNING task. The
    owning node's agent heartbeat loop delivers it into the live task
    dirs (every gang instance gets its copy); an instrumented workload
    drains to its next step boundary, forces a COMMITTED checkpoint,
    and exits EXIT_PREEMPTED — requeued at full retry budget. Returns
    False when the task is not in a preemptible state (or a concurrent
    transition won the merge). Idempotent: re-stamping an already
    pending request is a no-op (one drain per request).

    ``leader_epoch`` is the preempt-sweep term's fencing epoch
    (state/leases.py): stamped into the request and the notice event
    so every stamp is attributable to exactly one leadership term —
    the partition drill's zero-double-fire invariant reads it.
    Manual CLI preemptions carry None (no term to fence).

    ``defer_notice``: return the notice-emitting closure (truthy)
    instead of publishing the TASK_PREEMPT_NOTICE event here — for
    the leader sweep, whose post-write fence check may RETRACT a
    stamp that landed after its term ended; emitting eagerly would
    leave a dangling notice event for a preemption that never
    happened. The caller invokes the closure once the stamp is known
    to stand."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    task = get_task(store, pool_id, job_id, task_id)
    if task.get("state") not in ("assigned", "running"):
        return False
    if task.get(names.TASK_COL_PREEMPT_REQUEST):
        return True  # already pending; one request, one drain
    request = {
        "requested_at": util.datetime_utcnow_iso(),
        "reason": reason or "preempted by scheduler",
        "by_job_id": by_job_id, "by_task_id": by_task_id,
        "leader_epoch": leader_epoch,
    }
    try:
        store.merge_entity(
            names.TABLE_TASKS, names.task_pk(pool_id, job_id),
            task_id, {names.TASK_COL_PREEMPT_REQUEST: request},
            if_match=task["_etag"])
    except (EtagMismatchError, NotFoundError):
        return False

    def _emit_notice() -> None:
        goodput_events.emit(
            store, pool_id, goodput_events.TASK_PREEMPT_NOTICE,
            job_id=job_id, task_id=task_id,
            attrs={"reason": request["reason"],
                   "by_job_id": by_job_id, "by_task_id": by_task_id,
                   "leader_epoch": leader_epoch},
            trace_id=task.get(trace_ctx.COL_TRACE_ID),
            span_id=task.get(trace_ctx.COL_TRACE_SPAN))
        logger.warning("preempt requested for %s/%s: %s", job_id,
                       task_id, request["reason"])

    if defer_notice:
        return _emit_notice
    _emit_notice()
    return True


def list_task_files(store: StateStore, pool_id: str, job_id: str,
                    task_id: str) -> list[str]:
    """List a task's uploaded files (data files list analog)."""
    prefix = names.task_output_key(pool_id, job_id, task_id, "")
    return [k[len(prefix):] for k in store.list_objects(prefix)]


def delete_task(store: StateStore, pool_id: str, job_id: str,
                task_id: str, require_terminal: bool = True) -> None:
    """Delete a task's entity and its uploaded objects (tasks del
    analog). Non-terminal tasks must be terminated first."""
    task = get_task(store, pool_id, job_id, task_id)
    if require_terminal and task.get("state") not in \
            names.TERMINAL_TASK_STATES:
        raise ValueError(
            f"task {task_id} is {task.get('state')}; terminate first")
    prefix = names.task_output_key(pool_id, job_id, task_id, "")
    for key in store.list_objects(prefix):
        store.delete_object(key)
    store.delete_entity(names.TABLE_TASKS,
                        names.task_pk(pool_id, job_id), task_id)


def delete_job(store: StateStore, pool_id: str, job_id: str) -> None:
    get_job(store, pool_id, job_id)
    pk = names.task_pk(pool_id, job_id)
    for task in list(store.query_entities(names.TABLE_TASKS,
                                          partition_key=pk)):
        delete_task(store, pool_id, job_id, task["_rk"],
                    require_terminal=False)
    for row in list(store.query_entities(names.TABLE_JOBPREP,
                                         partition_key=pk)):
        store.delete_entity(names.TABLE_JOBPREP, pk, row["_rk"])
    store.delete_entity(names.TABLE_JOBS, pool_id, job_id)


def job_stats(store: StateStore, pool_id: str,
              job_id: Optional[str] = None) -> dict:
    """jobs stats analog (batch.py:1972), plus queue/run aggregates
    sourced from the goodput event log: queue_seconds sums queued
    spans (submit->first claim; requeue->re-claim for retries, one
    span per gang regardless of width), run_seconds sums running
    spans (node-seconds: gang tasks contribute one span per
    instance)."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    jobs = ([get_job(store, pool_id, job_id)] if job_id
            else list_jobs(store, pool_id))
    stats = {"jobs": len(jobs), "tasks": 0, "by_state": {},
             "wall_seconds_total": 0.0,
             "queue_seconds": 0.0, "run_seconds": 0.0}
    job_ids = {job["_rk"] for job in jobs}
    for job in jobs:
        for task in list_tasks(store, pool_id, job["_rk"]):
            stats["tasks"] += 1
            state = task.get("state", "pending")
            stats["by_state"][state] = stats["by_state"].get(state, 0) + 1
            stats["wall_seconds_total"] += float(
                task.get("wall_seconds", 0.0) or 0.0)
    # One unsorted pass over the pool's event partition (no need for
    # events.query's time ordering here; the log is bounded by
    # `goodput prune` retention).
    for event in store.query_entities(names.TABLE_GOODPUT,
                                      partition_key=pool_id):
        if event.get("job_id") not in job_ids or \
                event.get("kind") not in (goodput_events.TASK_QUEUED,
                                          goodput_events.TASK_RUNNING):
            continue
        duration = max(0.0, float(event.get("end", 0.0))
                       - float(event.get("start", 0.0)))
        if event.get("kind") == goodput_events.TASK_QUEUED:
            stats["queue_seconds"] += duration
        else:
            stats["run_seconds"] += duration
    return stats

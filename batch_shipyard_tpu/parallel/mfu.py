"""Model-FLOPs-utilization accounting for the bench pipeline.

The reference publishes raw throughput only (BASELINE.md: images/sec on
16xV100); a TPU framework must also answer "what fraction of the MXU's
peak did that throughput buy?" — the number the scaling-book methodology
tunes against. This module holds the analytic FLOPs models for the two
headline workloads plus the MFU division, with the per-chip peak coming
from parallel/topology.py's generation table keyed on the live
``jax.device_kind``.

Conventions (stated so the denominators are auditable):
- One multiply-accumulate = 2 FLOPs.
- Training step = 3x forward (1 fwd + 2 bwd, the standard accounting).
- Transformer follows the PaLM-appendix formula: 6*N FLOPs per trained
  token for the parameter matmuls (N = params including the tied
  embedding, whose output projection IS a per-token matmul here) plus
  the attention score/value term 12*L*T*d_model, halved for causal
  masking (average visible context T/2).
"""

from __future__ import annotations

from typing import Any, Optional

# torchvision-standard ResNet-50 forward cost at 224x224: 4.09 GMACs.
_RESNET50_FWD_MACS_224 = 4.09e9


def resnet50_train_flops_per_image(image_size: int = 224) -> float:
    """Analytic ResNet-50 training FLOPs per image. Conv cost scales
    with spatial area, so non-224 sizes scale quadratically (exact for
    everything but the fixed-cost final FC, which is <0.1%)."""
    fwd = 2.0 * _RESNET50_FWD_MACS_224 * (image_size / 224.0) ** 2
    return 3.0 * fwd


def transformer_param_count(config: Any) -> int:
    """Parameter count of models/transformer.TransformerLM from its
    config — kept in lockstep with the module tree (embed + per-block
    qkv/out + SwiGLU gate/up/down + RMSNorm scales + final norm; the
    output projection is the tied embedding). Oracle-tested against a
    real ``model.init`` in tests/test_mfu.py so it cannot drift."""
    d, v = config.d_model, config.vocab_size
    h, dh, ff = config.n_heads, config.d_head, config.d_ff
    per_block = (
        3 * d * h * dh        # q, k, v projections
        + h * dh * d          # output projection
        + 3 * d * ff          # SwiGLU gate, up, down
        + 2 * d               # two RMSNorm scales
    )
    return v * d + config.n_layers * per_block + d  # + final norm


def transformer_train_flops_per_token(config: Any, seq_len: int,
                                      causal: bool = True) -> float:
    """PaLM-style FLOPs/token: 6*N for parameter matmuls (fwd 2N +
    bwd 4N) + attention 12*L*T*d (6*L*T*d causal)."""
    n = transformer_param_count(config)
    attn = 12.0 * config.n_layers * seq_len * config.d_model
    if causal:
        attn *= 0.5
    return 6.0 * n + attn


def mfu_pct(items_per_sec_per_chip: float, flops_per_item: float,
            peak_tflops_per_chip: Optional[float]) -> Optional[float]:
    """Achieved model FLOPs as a percentage of one chip's bf16 peak.
    None when the peak is unknown (non-TPU backend) — an absent number
    is honest, a made-up denominator is not."""
    if peak_tflops_per_chip is None or peak_tflops_per_chip <= 0:
        return None
    achieved = items_per_sec_per_chip * flops_per_item
    return 100.0 * achieved / (peak_tflops_per_chip * 1e12)

"""Chaos drill: run a seeded fault schedule against a real fakepod
pool and assert the self-healing invariants.

The drill is the proof the recovery layer demands: it builds a pool of
REAL NodeAgents (threads over a shared state store), submits a batch
of watchdog-protected tasks, replays a ChaosPlan's injections at their
scheduled offsets — wedges, mid-run kills, node preemptions, heartbeat
blackouts, store faults — then verifies that the system healed:

  * every task reached ``completed`` (bounded retries beat every
    injected fault),
  * exactly-once effects (each task's output holds exactly its line),
  * no orphaned coordination state (gang rows, queue messages),
  * the goodput partition stayed exact (productive + badput +
    overlapped == wall) — chaos may move seconds between categories
    but can never lose any.

Used by `shipyard chaos drill`, tools/chaos_drill.py, and the test
suite (tests/test_chaos_recovery.py drives small, fast drills).
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import sys
import threading
import time
from typing import Optional

from batch_shipyard_tpu.chaos import injectors as injectors_mod
from batch_shipyard_tpu.chaos.plan import ChaosPlan
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

POOL_ID = "chaos-drill"
JOB_ID = "drill"
# Every drill workload carries one real gang task alongside the
# regular tasks: without it TABLE_GANGS is empty by construction and
# the "no orphaned gang rows" invariant would be vacuously true — a
# leak in _clear_gang_rows/_recover_broken_gang under chaos would
# pass every drill.
GANG_TASK_ID = "g000"
GANG_INSTANCES = 2


def run_drill(seed: int = 0, tasks: int = 16,
              accelerator: str = "v5litepod-16",
              duration: float = 4.0,
              kinds: Optional[tuple[str, ...]] = None,
              injections_per_kind: int = 1,
              task_sleep: float = 1.2,
              wait_timeout: float = 120.0,
              plan: Optional[ChaosPlan] = None) -> dict:
    """Run one drill; returns the report dict (invariants + plan
    fingerprint + goodput decomposition). Raises AssertionError when
    an invariant does not hold.

    Defaults are tuned so the submitted work SPANS the injection
    window (tasks * task_sleep ≈ 2-3x duration / slots): a kill
    scheduled at t=3 must find a victim actually running, or the
    drill proves nothing about the kill paths. ``tasks`` counts the
    regular tasks; one gang task (``GANG_TASK_ID``) always rides
    along so the gang-row cleanup invariant is actually exercised."""
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    raw_store = MemoryStateStore()
    chaos_store = injectors_mod.ChaosStore(raw_store)
    # Agents live on the chaos-wrapped store (they must survive the
    # faults); the drill driver itself orchestrates through the raw
    # store so an injected error never masquerades as a driver bug.
    substrate = FakePodSubstrate(chaos_store, node_stale_seconds=3.0)
    substrate.agent_kwargs = {
        "retry_backoff_base": 0.2, "retry_backoff_cap": 2.0,
        # The claimed-message window floors crashed-node recovery
        # latency; production's 60s would dominate a seconds-scale
        # drill.
        "claim_visibility_seconds": 5.0,
        # Fast janitor cadence: a cleanup lost to an injected store
        # fault must be swept inside the invariant-check window.
        "gang_sweep_interval": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "tpu": {"accelerator_type": accelerator},
        "task_slots_per_node": 2,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    if plan is None:
        plan = ChaosPlan.generate(
            seed, duration=duration,
            num_nodes=pool.tpu.total_workers if pool.tpu else 4,
            kinds=kinds, injections_per_kind=injections_per_kind)
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    try:
        pool_mgr.create_pool(raw_store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": f"t{i:03d}",
                       "command": (f"sleep {task_sleep} && "
                                   f"echo drill-{i}"),
                       "max_task_retries": 8,
                       "progress_deadline_seconds": 2}
                      for i in range(tasks)]
                     + [{"id": GANG_TASK_ID,
                         "command": (f"sleep {task_sleep} && "
                                     "echo drill-gang"),
                         "max_task_retries": 8,
                         "progress_deadline_seconds": 2,
                         "multi_instance": {
                             "num_instances": GANG_INSTANCES}}],
        }]})
        started = time.monotonic()
        jobs_mgr.add_jobs(raw_store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, chaos_store, report),
            daemon=True, name="chaos-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            raw_store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=max(0.0, duration -
                                (time.monotonic() - started)) + 5.0)
        _check_invariants(raw_store, task_rows, tasks, report)
    finally:
        substrate.stop_all()
    return report


def run_preemption_drill(seed: int = 0, instances: int = 4,
                         steps: int = 60, step_seconds: float = 0.08,
                         duration: float = 4.0,
                         wait_timeout: float = 120.0) -> dict:
    """Preemption-recovery drill: a seeded node_preempt_notice
    schedule preempts a RUNNING ``instances``-wide gang mid-training
    (the preempt_probe workload — real beats, real step windows, the
    real COMMITTED-marker commit protocol). Asserts the elastic-
    training acceptance invariants:

      * the gang drained cooperatively, requeued with the distinct
        preempted status, and resumed from the forced COMMITTED
        checkpoint with ZERO lost steps beyond the barrier (the step
        ledger is contiguous and replay-free),
      * the retry budget was untouched (retries == 0) and
        preempt_count advanced,
      * node health was not debited (an externally-caused exit says
        nothing about the node),
      * the goodput partition stayed exact AND the
        preemption_recovery leg is actually populated.

    Raises AssertionError on any violation; returns the report."""
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    # Fast heartbeats: preempt-request delivery rides the heartbeat
    # loop, and the drill's notice windows must dwarf one beat.
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {"claim_visibility_seconds": 5.0,
                              "gang_sweep_interval": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=duration,
                              num_nodes=instances,
                              kinds=("node_preempt_notice",))
    # Deterministic cooperation: widen every notice window well past
    # one heartbeat + one step, so the drill always exercises the
    # COOPERATIVE path (the hard-kill fallback is the generic drill's
    # territory). Pure function of the seed, still.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, params=tuple(sorted(
            {**dict(inj.params), "notice": 2.5}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": GANG_TASK_ID,
                       "command": (
                           f"{sys.executable} -m batch_shipyard_tpu"
                           f".workloads.preempt_probe "
                           f"--steps {steps} "
                           f"--step-seconds {step_seconds} "
                           f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": repo_root},
                       "max_task_retries": 3,
                       "multi_instance": {
                           "num_instances": instances,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        started = time.monotonic()
        jobs_mgr.add_jobs(store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, None, report),
            daemon=True, name="chaos-preempt-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        _check_preemption_invariants(store, task_rows, ckpt, steps,
                                     report)
    finally:
        substrate.stop_all()
    return report


def _check_preemption_invariants(store, task_rows: list, ckpt: str,
                                 steps: int, report: dict) -> None:
    invariants = report["invariants"]
    task = task_rows[0]
    invariants["state"] = task.get("state")
    assert task.get("state") == "completed", task
    # Full budget preserved: preemption consumed ZERO retries.
    invariants["retries"] = int(task.get("retries", 0))
    invariants["preempt_count"] = int(
        task.get(names.TASK_COL_PREEMPT_COUNT, 0) or 0)
    assert invariants["retries"] == 0, (
        f"preemption consumed retry budget: {task}")
    assert invariants["preempt_count"] >= 1, (
        f"drill never preempted the gang: {report['applied']}")
    # Zero lost steps beyond the barrier: the writer's step ledger is
    # contiguous (each preempted attempt's commit is exactly where
    # the next attempt resumed — no replay, no gap) and covers every
    # step exactly once.
    with open(ckpt + ".steps.log", encoding="utf-8") as fh:
        ledger = [line.split() for line in fh if line.strip()]
    invariants["step_ledger"] = [" ".join(parts) for parts in ledger]
    cursor = 0
    for _inst, span, _status in ledger:
        lo, hi = span.split("..")
        assert int(lo) == cursor, (
            f"step ledger not contiguous (lost or replayed steps): "
            f"{invariants['step_ledger']}")
        cursor = int(hi)
    assert cursor == steps, invariants["step_ledger"]
    assert ledger[-1][2] == "completed", invariants["step_ledger"]
    # Node health untouched: externally-caused exits are neutral.
    for node in store.query_entities(names.TABLE_NODES,
                                     partition_key=POOL_ID):
        health = float(node.get(names.NODE_COL_HEALTH, 1.0) or 1.0)
        assert health >= 1.0, (
            f"preemption debited node health: "
            f"{node['_rk']}={health}")
        assert not node.get(names.NODE_COL_QUARANTINED), node
    invariants["node_health_untouched"] = True
    # Goodput: partition exact AND the preemption_recovery leg is
    # actually populated by the drill (the recovery interval from
    # preempted exit to re-claim).
    pool_report = accounting.pool_report(store, POOL_ID,
                                         include_jobs=False)
    total = (pool_report["productive_seconds"]
             + sum(pool_report["badput_seconds"].values())
             + sum(pool_report["overlapped_seconds"].values()))
    invariants["goodput_wall_seconds"] = pool_report["wall_seconds"]
    invariants["goodput_partition_total"] = total
    assert abs(total - pool_report["wall_seconds"]) <= max(
        1e-6 * max(1.0, pool_report["wall_seconds"]), 1e-6), (
        f"goodput partition broke: {total} != "
        f"{pool_report['wall_seconds']}")
    recovery = pool_report["badput_seconds"].get(
        "preemption_recovery", 0.0)
    invariants["preemption_recovery_seconds"] = recovery
    assert recovery > 0.0, (
        f"preemption_recovery not populated: "
        f"{pool_report['badput_seconds']}")
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def _inject_schedule(plan: ChaosPlan, started: float, substrate,
                     chaos_store, report: dict) -> None:
    for injection in plan.injections:
        delay = injection.at - (time.monotonic() - started)
        if delay > 0:
            time.sleep(delay)
        try:
            record = injectors_mod.apply_injection(
                injection, substrate, POOL_ID, store=chaos_store)
        except Exception as exc:  # noqa: BLE001 - record, keep going
            record = {"kind": injection.kind, "error": str(exc)}
        logger.info("chaos injection %s", record)
        report["applied"].append(record)


def _check_invariants(store, task_rows: list, expected: int,
                      report: dict) -> None:
    invariants = report["invariants"]
    # 1. Every task completed (exactly the expected set, each once —
    # entities are unique by id, so completion is single-valued).
    states: dict = {}
    for task in task_rows:
        states[task.get("state")] = states.get(task.get("state"), 0) + 1
    invariants["tasks"] = states
    assert states == {"completed": expected + 1}, (
        f"drill tasks not all completed: {states}")
    # 2. Exactly-once effects: the final output of each task is its
    # single line (a double-completed task would have been re-run
    # after success and is a claim-protocol bug).
    for task in task_rows:
        task_id = task["_rk"]
        if task_id == GANG_TASK_ID:
            # Gang instance 0's final output holds its single line
            # (a recovered attempt overwrites the same key, so this
            # checks the LAST attempt ran cleanly).
            out = jobs_mgr.get_task_output(
                store, POOL_ID, JOB_ID, task_id, instance=0)
            assert out.strip() == b"drill-gang", (
                f"{task_id}: unexpected gang output {out!r}")
            continue
        index = int(task_id[1:])
        out = jobs_mgr.get_task_output(store, POOL_ID, JOB_ID, task_id)
        assert out.strip() == f"drill-{index}".encode(), (
            f"{task_id}: unexpected output {out!r}")
    # 3. No orphaned coordination state: gang rows are gone and the
    # task queues drain, each within a bounded window (terminal-task
    # messages get deleted on next delivery; a gang cleanup lost to
    # an injected store fault is repaired by the agents' orphan
    # janitor sweep). The workload's gang task guarantees gang rows
    # EXISTED during the drill, so an empty table here proves
    # cleanup, not absence of gangs.
    deadline = time.monotonic() + 30.0
    queues = names.task_queues(POOL_ID, 1)
    while True:
        leftover_gangs = list(store.query_entities(names.TABLE_GANGS))
        depth = sum(store.queue_length(q) for q in queues)
        if (not leftover_gangs and depth == 0) or \
                time.monotonic() >= deadline:
            break
        time.sleep(0.25)
    invariants["orphaned_gang_rows"] = len(leftover_gangs)
    assert not leftover_gangs, leftover_gangs
    invariants["queue_depth"] = depth
    assert depth == 0, f"undrained task queues: {depth} messages"
    # 4. Goodput partition exactness: chaos moves time between
    # categories; it must never create or lose a second.
    pool_report = accounting.pool_report(store, POOL_ID,
                                         include_jobs=False)
    total = (pool_report["productive_seconds"]
             + sum(pool_report["badput_seconds"].values())
             + sum(pool_report["overlapped_seconds"].values()))
    invariants["goodput_wall_seconds"] = pool_report["wall_seconds"]
    invariants["goodput_partition_total"] = total
    assert abs(total - pool_report["wall_seconds"]) <= max(
        1e-6 * max(1.0, pool_report["wall_seconds"]), 1e-6), (
        f"goodput partition broke: {total} != "
        f"{pool_report['wall_seconds']}")
    invariants["retries"] = pool_report.get("retries", 0)
    invariants["backoff_seconds"] = (
        pool_report["badput_seconds"].get("backoff", 0.0))
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
        "overlapped_seconds": pool_report["overlapped_seconds"],
    }
    invariants["ok"] = True

"""Input pipeline: sharded datasets with background host->device
prefetch.

SURVEY.md section 7 flags input-pipeline parity as a hard part of the
ResNet/ImageNet baseline ("orchestrator must make data locality
configurable"). This loader covers the workload side:

  - ``ShardedDataset``: enumerate shard files from a local directory
    (staged by input_data/gcsfuse), partitioned across jax processes
    (each pod worker reads only its slice — data parallel by
    construction). .npz shards yield their named arrays (e.g.
    images/labels); bare .npy shards yield under the key ``data``;
  - ``prefetch_to_device``: a background thread that stages the next
    batches onto the device (with the mesh sharding applied) while the
    current step computes, hiding host->HBM transfer latency — the
    tf.data.prefetch analog without TensorFlow.

Synthetic mode keeps benches and tests hermetic.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class ShardedDataset:
    """Iterate batches from .npy/.npz shards, partitioned across
    processes."""

    def __init__(self, shard_dir: str, batch_size: int,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 loop: bool = True, seed: int = 0) -> None:
        self.shard_dir = shard_dir
        self.batch_size = batch_size
        self.loop = loop
        self.seed = seed
        pidx = (process_index if process_index is not None
                else jax.process_index())
        pcnt = (process_count if process_count is not None
                else jax.process_count())
        shards = sorted(
            os.path.join(shard_dir, name)
            for name in os.listdir(shard_dir)
            if name.endswith((".npy", ".npz")))
        if not shards:
            raise ValueError(f"no .npy/.npz shards in {shard_dir}")
        # Round-robin shard assignment across pod workers.
        self.shards = shards[pidx::pcnt]
        if not self.shards:
            raise ValueError(
                f"process {pidx}/{pcnt}: no shards assigned "
                f"({len(shards)} total)")

    def _load(self, path: str) -> dict[str, np.ndarray]:
        if path.endswith(".npz"):
            with np.load(path) as data:
                return {k: data[k] for k in data.files}
        return {"data": np.load(path)}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.RandomState(self.seed)
        epoch = 0
        while True:
            order = list(self.shards)
            rng.shuffle(order)
            carry: dict[str, list] = collections.defaultdict(list)
            carried = 0
            for path in order:
                arrays = self._load(path)
                n = len(next(iter(arrays.values())))
                start = 0
                while start < n:
                    take = min(self.batch_size - carried, n - start)
                    for key, arr in arrays.items():
                        carry[key].append(arr[start:start + take])
                    carried += take
                    start += take
                    if carried == self.batch_size:
                        yield {k: np.concatenate(v)
                               for k, v in carry.items()}
                        carry = collections.defaultdict(list)
                        carried = 0
            epoch += 1
            if not self.loop:
                return


def synthetic_batches(make_batch: Callable[[int], dict],
                      ) -> Iterator[dict]:
    """Infinite synthetic batches (hermetic benches)."""
    step = 0
    while True:
        yield make_batch(step)
        step += 1


def place_global(batch: dict, sharding) -> dict:
    """Place one host-LOCAL batch as a (possibly multi-host) global
    array. Single process: plain device_put. Multi-process (gang task
    across a pod): each process contributes its local slice of the
    global batch via make_array_from_process_local_data — the batch
    dim of the global array is process_count * local rows."""
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return {
        key: jax.make_array_from_process_local_data(
            sharding if not isinstance(sharding, dict)
            else sharding[key], np.asarray(arr))
        for key, arr in batch.items()
    }


def prefetch_to_device(batches: Iterator[dict], sharding,
                       depth: int = 2) -> Iterator[dict]:
    """Stage upcoming batches onto device(s) on a background thread.

    batches yield host-local arrays; sharding is a jax Sharding (or a
    dict of them per batch key). On a mesh each host's slice lands
    directly in the right HBM shards (multi-host aware via
    place_global). The producer thread shuts down when the consumer
    abandons or closes the generator (no leaked device batches).
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _SENTINEL = object()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in batches:
                if stop.is_set():
                    return
                if not _put(place_global(batch, sharding)):
                    return
        except Exception as exc:  # noqa: BLE001
            _put(exc)
            return
        _put(_SENTINEL)

    thread = threading.Thread(target=producer, daemon=True,
                              name="prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()


def write_synthetic_imagenet_shards(
        out_dir: str, num_shards: int = 4, per_shard: int = 512,
        image_size: int = 64, num_classes: int = 1000,
        seed: int = 0) -> list[str]:
    """Materialize synthetic ImageNet-shaped .npz shards (tooling for
    recipes/tests; real data lands here via input_data or gcsfuse)."""
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.RandomState(seed)
    paths = []
    for idx in range(num_shards):
        path = os.path.join(out_dir, f"shard_{idx:05d}.npz")
        np.savez(
            path,
            images=rng.randint(
                0, 255, (per_shard, image_size, image_size, 3),
                dtype=np.uint8),
            labels=rng.randint(0, num_classes, (per_shard,),
                               dtype=np.int32))
        paths.append(path)
    return paths

"""ML Productivity Goodput accounting (arxiv 2502.06982).

A fleet-wide productivity event log + the accounting engine that folds
it into the paper's decomposition::

    goodput = availability x resource x program

``events``     — typed interval event API over the state store
                 (TABLE_GOODPUT) plus a process-local JSONL recorder
                 for workloads running inside tasks.
``accounting`` — pure functions over event dicts: overlapping-interval
                 resolution, badput breakdown by category, per-job /
                 per-pool / fleet rollups, waterfall + Prometheus
                 rendering.
"""

"""Chunked cross-entropy tests: Pallas kernel (interpret mode) and
scan-chunked XLA path vs the dense oracle — forward and gradients —
plus the lm_loss_chunked delegation, validation-marker-gated auto
dispatch (ops/kernel_select), and the silicon-proof dry-run."""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.ops import chunked_loss as cl
from batch_shipyard_tpu.ops import kernel_select, ring_attention

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _dense_loss(h, e, t, ignore_id=-1):
    d = h.shape[-1]
    logits = (h.reshape(-1, d).astype(jnp.float32)
              @ e.astype(jnp.float32).T)
    tg = t.reshape(-1)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, tg[:, None].clip(0), axis=-1)[:, 0]
    mask = (tg != ignore_id)
    return jnp.sum((lse - gold) * mask) / jnp.maximum(
        jnp.sum(mask), 1)


def _rand(b, t, d, v, seed=0):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(b, t, d), jnp.float32)
    e = jnp.asarray(rng.randn(v, d) / np.sqrt(d), jnp.float32)
    tg = jnp.asarray(rng.randint(0, v, (b, t)), jnp.int32)
    return h, e, tg


@pytest.mark.parametrize("impl", ["xla", "interpret"])
@pytest.mark.parametrize(
    # Ragged rows (b*t % 128 != 0) and ragged vocab (v % v_chunk != 0)
    # exercise the padding + in-kernel tail-mask paths.
    "b,t,d,v", [(2, 128, 128, 1024), (2, 96, 128, 700),
                (1, 64, 256, 512)])
def test_loss_matches_dense_oracle(impl, b, t, d, v):
    h, e, tg = _rand(b, t, d, v)
    tg = tg.at[0, :5].set(-1)  # exercise the ignore mask
    got = jax.jit(lambda h, e: cl.chunked_softmax_xent(
        h, e, tg, impl=impl))(h, e)
    want = _dense_loss(h, e, tg)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
def test_grads_match_dense_oracle(impl):
    h, e, tg = _rand(2, 96, 128, 700, seed=3)
    tg = tg.at[1, -9:].set(-1)

    def loss(h, e):
        return cl.chunked_softmax_xent(h, e, tg, impl=impl)

    gh, ge = jax.grad(loss, argnums=(0, 1))(h, e)
    rh, re = jax.grad(lambda h, e: _dense_loss(h, e, tg),
                      argnums=(0, 1))(h, e)
    for a, b_ in ((gh, rh), (ge, re)):
        rel = (np.linalg.norm(np.asarray(a - b_))
               / max(np.linalg.norm(np.asarray(b_)), 1e-30))
        assert rel < 1e-5


def test_all_tokens_ignored_is_finite():
    h, e, tg = _rand(1, 128, 128, 512, seed=5)
    tg = jnp.full_like(tg, -1)
    for impl in ("xla", "interpret"):
        got = cl.chunked_softmax_xent(h, e, tg, impl=impl)
        assert float(got) == 0.0
        gh = jax.grad(lambda h: cl.chunked_softmax_xent(
            h, e, tg, impl=impl))(h)
        assert np.all(np.isfinite(np.asarray(gh)))
        assert float(jnp.sum(jnp.abs(gh))) == 0.0


def test_lm_loss_chunked_delegates_and_matches():
    from batch_shipyard_tpu.models import transformer as tfm
    h, e, tg = _rand(2, 64, 128, 512, seed=7)
    got = tfm.lm_loss_chunked(h, e, tg)
    want = _dense_loss(h, e, tg)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_lane_misaligned_dim_falls_back_to_xla():
    # d % 128 != 0 must silently take the XLA path, not crash.
    h, e, tg = _rand(1, 64, 96, 300, seed=9)
    got = cl.chunked_softmax_xent(h, e, tg, impl="pallas")
    want = _dense_loss(h, e, tg)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


# -- validation-marker dispatch (ops/kernel_select) -----------------

def test_auto_resolves_xla_on_cpu_even_with_marker(tmp_path,
                                                   monkeypatch):
    marker = tmp_path / "KERNEL_VALIDATION.json"
    marker.write_text(json.dumps({
        "flash_ring": {"ok": True, "backend": "tpu"},
        "chunked_cross_entropy": {"ok": True, "backend": "tpu"}}))
    monkeypatch.setenv(kernel_select.MARKER_ENV, str(marker))
    # kernel_validated sees the tpu-backed pass...
    assert kernel_select.kernel_validated("flash_ring")
    # ...but auto still refuses Pallas paths on the cpu backend.
    assert kernel_select.resolve_auto("flash_ring",
                                      pallas_impl="flash") == "xla"
    assert ring_attention.resolve_ring_impl("auto") == "xla"


def test_cpu_backed_marker_does_not_validate(tmp_path, monkeypatch):
    marker = tmp_path / "KERNEL_VALIDATION.json"
    marker.write_text(json.dumps({
        "flash_ring": {"ok": True, "backend": "cpu"}}))
    monkeypatch.setenv(kernel_select.MARKER_ENV, str(marker))
    assert not kernel_select.kernel_validated("flash_ring")


def test_ring_impl_env_override_and_priority(monkeypatch):
    monkeypatch.setenv("SHIPYARD_RING_IMPL", "flash")
    assert ring_attention.resolve_ring_impl("auto") == "flash"
    # Explicit impl beats the env var.
    assert ring_attention.resolve_ring_impl("xla") == "xla"
    monkeypatch.setenv("SHIPYARD_RING_IMPL", "bogus")
    with pytest.raises(ValueError):
        ring_attention.resolve_ring_impl("auto")


def test_missing_marker_means_not_validated(monkeypatch, tmp_path):
    monkeypatch.setenv(kernel_select.MARKER_ENV,
                       str(tmp_path / "absent.json"))
    assert kernel_select.kernel_validation() == {}
    assert not kernel_select.kernel_validated("flash_ring")


# -- silicon-proof pipeline dry run ---------------------------------

def test_silicon_proof_dry_run_writes_full_skeleton(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools/silicon_proof.py"),
         "--dry-run", "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(
        (tmp_path / "SILICON_PROOF.json").read_text())
    assert report["dry_run"] is True
    names = [p["phase"] for p in report["phases"]]
    assert names == ["probe", "kernel_checks", "flash_flip",
                     "ring_collectives", "tuning_ab", "final_bench",
                     "serving_speculative", "checkpoint_overhead",
                     "goodput", "compile_warm", "chaos_drill"]
    assert all(p["status"] == "dry_run" for p in report["phases"])
    # The ring-collectives kernel phase's skeleton names every metric
    # and carries the explicit unreachable marker benchgen renders
    # (claims are labeled, not implied).
    ring = report["phases"][3]
    assert "bench.py" in ring["command"]
    assert "ring_collectives" in ring["command"]
    assert "dry-run skeleton" in ring["note"]
    assert set(ring["metrics"]) == {
        "mode", "ring", "chips", "numeric_ok",
        "best_all_gather_gbps", "best_reduce_scatter_gbps"}
    # The speculative serving phase's skeleton names every metric it
    # will emit, for both KV layouts.
    spec = report["phases"][6]
    assert "bench.py" in spec["command"]
    assert "serving_speculative" in spec["command"]
    for variant in ("dense", "paged"):
        assert set(spec["metrics"][variant]) == {
            "tokens_per_second", "ttft_ms_p50", "tpot_ms_p50",
            "acceptance_rate"}
    # The warm-start compilation phase's skeleton names every metric
    # benchgen binds to.
    compile_warm = report["phases"][9]
    assert "compile_warm" in compile_warm["command"]
    assert set(compile_warm["metrics"]) == {
        "cold_ms", "warm_ms", "speedup", "cache_hits",
        "aot_first_step_ms", "steady_step_ms"}
    # The chaos-drill phase's skeleton names the recovery invariants
    # benchgen binds to (docs/30-fault-tolerance.md).
    chaos = report["phases"][10]
    assert "chaos_drill.py" in chaos["command"]
    assert set(chaos["metrics"]) == {"determinism",
                                     "injections_applied",
                                     "invariants"}
    assert set(chaos["metrics"]["invariants"]) == {
        "tasks", "orphaned_gang_rows", "queue_depth", "retries",
        "backoff_seconds"}
    # The tuning plan must cover every profile with a runnable command.
    plan = report["phases"][4]["plan"]
    from batch_shipyard_tpu.parallel.tuning import PROFILES
    assert set(plan) == set(PROFILES)
    assert all("bench.py --quick" in cmd for cmd in plan.values())


def test_benchgen_renders_from_artifacts(tmp_path):
    """tools/benchgen.py renders the measured-numbers page from the
    repo's real bench artifacts (docs depth pass: the page is
    generated, so it cannot rot)."""
    out = tmp_path / "bench.md"
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools/benchgen.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    text = out.read_text()
    assert "# Measured performance" in text
    assert "GENERATED" in text
    assert "## Headline metric by round" in text
    # The honest state renders too: either real numbers or the
    # explicit unreachable status.
    assert ("images/sec/chip" in text or
            "accelerator unreachable" in text)

"""Serving workload: HTTP front end over the continuous-batching
engine, with an optional built-in Poisson load benchmark.

Recipe command (Serving-ContinuousBatching):
    python -m batch_shipyard_tpu.workloads.serve \
        --num-slots 8 --max-decode-len 512 \
        --loadgen 64 --rate 16 --report latency_report.json

Without --loadgen the server runs until terminated (a long-lived
serving task); with it, the benchmark runs against the in-process
server, writes the latency-histogram JSON report, prints it as the
final stdout line, and exits nonzero if any request failed.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import jax
import jax.numpy as jnp

from batch_shipyard_tpu import compilecache
from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.server import ServingFrontEnd


def warm_engine(args, engine: serving.ContinuousBatcher) -> None:
    """Warm one engine before its front end takes traffic: every
    prefill bucket via throwaway requests, or — with --aot-precompile
    and the persistent cache enabled — from abstract shapes alone, so
    no request is burned and restarts deserialize instead of
    compiling. AOT executables are discarded (their value IS the
    persistent cache they populate), so without an enabled cache the
    flag would leave the engine cold AND double-compile — fall back
    to the request-driven warm-up instead."""
    if args.aot_precompile and compilecache.current() is not None:
        engine.precompile()
    else:
        engine.warmup()


def build_config(args) -> tfm.TransformerConfig:
    return tfm.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=args.d_ff,
        max_seq_len=args.max_decode_len, dtype=jnp.bfloat16,
        kv_cache_dtype=args.kv_cache_dtype)


def build_params(args, config: tfm.TransformerConfig):
    """Init (or checkpoint-restore) ONE param tree — fleet mode
    shares it across every replica engine rather than paying the
    init/restore and a full weight copy per replica."""
    model = tfm.TransformerLM(config)
    params = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, 8), jnp.int32))["params"]
    if args.checkpoint_dir:
        # Serve trained weights (train_transformer --checkpoint-dir
        # artifacts); dims must match the model args.
        from batch_shipyard_tpu.workloads import checkpoint
        restored = checkpoint.restore_params(args.checkpoint_dir)
        if restored is None:
            raise SystemExit(
                f"no checkpoint found in {args.checkpoint_dir}")
        restored_params, step = restored
        import jax.tree_util as jtu
        want = jtu.tree_structure(params)
        got = jtu.tree_structure(restored_params)
        if want != got:
            raise SystemExit(
                "checkpoint params do not match the model "
                "architecture flags (tree structure differs)")
        mismatched = [
            f"{jtu.keystr(path)}: {tuple(t.shape)} != "
            f"{tuple(r.shape)}"
            for (path, t), (_path2, r) in zip(
                jtu.tree_flatten_with_path(params)[0],
                jtu.tree_flatten_with_path(restored_params)[0])
            if tuple(t.shape) != tuple(r.shape)]
        if mismatched:
            raise SystemExit(
                "checkpoint params do not match the model "
                "architecture flags (shape mismatch): "
                + "; ".join(mismatched[:4]))
        params = jax.tree_util.tree_map(
            lambda t, r: jnp.asarray(r, t.dtype), params,
            restored_params)
        print(f"serving checkpoint step {step} from "
              f"{args.checkpoint_dir}", flush=True)
    return params


def build_draft(args) -> serving.SpeculativeConfig:
    """Draft model spec for --speculative: a small dense-cache
    transformer sharing the target's vocab (random init unless
    --draft-checkpoint-dir points at trained draft weights — a random
    draft exercises the worst case: near-zero acceptance, every round
    falls back to the target's correction token)."""
    draft_config = tfm.TransformerConfig(
        vocab_size=args.vocab, d_model=args.draft_d_model,
        n_layers=args.draft_n_layers, n_heads=args.n_heads,
        d_head=args.draft_d_model // args.n_heads,
        d_ff=args.draft_d_ff or args.draft_d_model * 3,
        max_seq_len=args.max_decode_len, dtype=jnp.bfloat16,
        kv_cache_dtype=args.kv_cache_dtype)
    draft_args = argparse.Namespace(**vars(args))
    draft_args.seed = args.seed + 7
    draft_args.checkpoint_dir = args.draft_checkpoint_dir
    draft_params = build_params(draft_args, draft_config)
    return serving.SpeculativeConfig(draft_config, draft_params,
                                     gamma=args.gamma)


def build_slo(args):
    """Resolve the serving SLO configuration (config/settings.py
    serving_slo_settings): --slo-config default -> the built-in class
    table; --slo-config PATH -> a JSON config mapping with a
    serving.slo section; neither -> SLO scheduling off (requests pass
    through untargeted). CLI --shed-grace-ms / --tpot-stall-factor
    override the parsed values."""
    from batch_shipyard_tpu.config.settings import serving_slo_settings
    if not args.slo_config:
        return None
    if args.slo_config == "default":
        slo = serving_slo_settings(None)
    else:
        with open(args.slo_config, encoding="utf-8") as fh:
            slo = serving_slo_settings(json.load(fh))
    if args.shed_grace_ms is not None:
        slo = dataclasses.replace(slo,
                                  shed_grace_ms=args.shed_grace_ms)
    if args.tpot_stall_factor is not None:
        slo = dataclasses.replace(
            slo, tpot_stall_factor=args.tpot_stall_factor)
    return slo


def build_engine(args, config=None, params=None,
                 speculative=None, slo=None) -> serving.ContinuousBatcher:
    if config is None:
        config = build_config(args)
    if params is None:
        params = build_params(args, config)
    if speculative is None and args.speculative:
        speculative = build_draft(args)
    return serving.ContinuousBatcher(
        config, params, num_slots=args.num_slots,
        max_decode_len=args.max_decode_len,
        sampling=inf.SamplingConfig(temperature=args.temperature,
                                    top_k=args.top_k),
        seed=args.seed,
        kv_page_size=args.kv_page_size,
        kv_num_pages=args.kv_num_pages,
        overcommit=args.overcommit,
        prefill_chunk=args.prefill_chunk,
        prefix_cache=not args.no_prefix_cache,
        slo_shed_grace_ms=slo.shed_grace_ms if slo else None,
        tpot_stall_factor=(slo.tpot_stall_factor if slo else 4.0),
        speculative=speculative)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-layers", type=int, default=4)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--d-ff", type=int, default=1024)
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--max-decode-len", type=int, default=512)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kv-page-size", type=int, default=None)
    parser.add_argument("--kv-cache-dtype", default=None,
                        choices=["int8"],
                        help="Quantize the decode KV cache (dense "
                        "or paged pool) to int8: half the HBM per "
                        "token -> 2x slots/context")
    parser.add_argument("--kv-num-pages", type=int, default=None)
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="Chunked prefill segment length (bounds "
                        "long-prompt prefill memory; power of two)")
    parser.add_argument("--overcommit", action="store_true")
    parser.add_argument("--no-prefix-cache", action="store_true",
                        help="Disable cross-request prefix/KV-cache "
                        "reuse in the paged pool (the control arm of "
                        "BENCH_serving_slo)")
    parser.add_argument("--slo-config", default=None,
                        help="SLO scheduling config: 'default' for "
                        "the built-in class table, or a JSON config "
                        "file with a serving.slo section "
                        "(config/settings.py serving_slo_settings)")
    parser.add_argument("--shed-grace-ms", type=float, default=None,
                        help="Arm overload shedding: queued requests "
                        "past their TTFT deadline by this grace are "
                        "rejected 503 (requires --slo-config)")
    parser.add_argument("--tpot-stall-factor", type=float,
                        default=None,
                        help="Admission defers prefills that would "
                        "stall active decodes past this multiple of "
                        "the tightest TPOT target")
    # Speculative decoding inside the engine: a small draft model
    # proposes gamma tokens per slot per step; ONE batched target
    # forward verifies every slot's block; commits are per-slot
    # ragged. Greedy-exact — requires --temperature 0.
    parser.add_argument("--speculative", action="store_true",
                        help="Enable engine-integrated speculative "
                        "decoding (draft/verify per engine step; "
                        "greedy-exact)")
    parser.add_argument("--gamma", type=int, default=4,
                        help="Draft tokens proposed per slot per "
                        "engine step")
    parser.add_argument("--draft-d-model", type=int, default=256)
    parser.add_argument("--draft-n-layers", type=int, default=2)
    parser.add_argument("--draft-d-ff", type=int, default=None,
                        help="Draft MLP width (default 3x "
                        "draft-d-model)")
    parser.add_argument("--draft-checkpoint-dir", default=None,
                        help="Serve draft params from an Orbax "
                        "checkpoint (random init otherwise — the "
                        "worst-case acceptance demo)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8900)
    # Front-door hardening + drain (37-serving-resilience.md).
    parser.add_argument("--max-inflight", type=int, default=None,
                        help="Cap accepted-but-unfinished requests "
                        "per replica; excess gets 429 back-pressure "
                        "(resumes are exempt)")
    parser.add_argument("--io-timeout-s", type=float, default=None,
                        help="Per-connection socket read/write "
                        "deadline (a wedged client cannot pin a "
                        "handler thread)")
    parser.add_argument("--drain-grace-s", type=float, default=30.0,
                        help="On a preempt/evict notice, let "
                        "in-flight decodes finish for this long "
                        "before abandoning them to sibling resume")
    # Benchmark mode
    parser.add_argument("--loadgen", type=int, default=0,
                        help="Run N benchmark requests then exit")
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Arrival rate (req/s; diurnal peak)")
    parser.add_argument("--arrival", choices=("poisson", "diurnal"),
                        default="poisson",
                        help="Loadgen arrival process (diurnal "
                        "replays the fleet simulator's day/night "
                        "curve)")
    parser.add_argument("--shared-prefix-groups", type=int,
                        default=0,
                        help="Loadgen shared prompt-prefix groups "
                        "(exercises the prefix cache and affinity "
                        "routing)")
    parser.add_argument("--shared-prefix-len", type=int, default=0)
    parser.add_argument("--prompt-len", type=int, nargs=2,
                        default=(4, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--gen-tokens", type=int, nargs=2,
                        default=(8, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--report", default="latency_report.json")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="Serve params from the latest Orbax "
                             "checkpoint (train_transformer output)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="Run N replica engines behind the "
                             "queue-depth-aware fleet router "
                             "(models/router.py); the router binds "
                             "--host/--port")
    compilecache.add_compile_cache_args(parser)
    args = parser.parse_args()
    # Persistent compile cache before any engine construction: the
    # engine __init__ compiles nothing, but warm-up / precompile and
    # the first requests do, and pool restarts should hit warm.
    compilecache.enable_from_args(
        args, model_digest=compilecache.config_digest(
            build_config(args)))

    fronts = []
    router = None
    slo = build_slo(args)
    slo_classes = slo.class_targets() if slo else None
    if args.replicas > 1:
        # Fleet mode: replicas bind ephemeral loopback ports; the
        # router is the public surface (same wire API).
        from batch_shipyard_tpu.models.router import ServingRouter
        config = build_config(args)
        params = build_params(args, config)
        # Like the target params, the draft tree is built once and
        # shared across every replica engine.
        speculative = build_draft(args) if args.speculative else None
        engines = [build_engine(args, config, params, speculative,
                                slo=slo)
                   for _ in range(args.replicas)]
        # Warm every replica BEFORE it starts taking traffic (jit
        # compiles recorded as engine warm-up goodput; must run before
        # the front's engine thread owns the stepping). Same-config
        # replicas share the module-level jits, so replica 1 pays and
        # the rest reuse.
        for e in engines:
            warm_engine(args, e)
        fronts = [ServingFrontEnd(e, port=0,
                                  slo_classes=slo_classes,
                                  max_inflight=args.max_inflight,
                                  io_timeout_s=args.io_timeout_s,
                                  drain_grace_s=args.drain_grace_s
                                  ).start()
                  for e in engines]
        router = ServingRouter([f.url for f in fronts],
                               host=args.host,
                               port=args.port).start()
        url = router.url
        print(f"fleet router on {url} over {len(fronts)} "
              f"replica(s)", flush=True)
    else:
        engine = build_engine(args, slo=slo)
        warm_engine(args, engine)
        fronts = [ServingFrontEnd(engine, host=args.host,
                                  port=args.port,
                                  slo_classes=slo_classes,
                                  max_inflight=args.max_inflight,
                                  io_timeout_s=args.io_timeout_s,
                                  drain_grace_s=args.drain_grace_s
                                  ).start()]
        url = fronts[0].url
        print(f"serving on {url}", flush=True)
    # A preempt/evict notice (agent/preemption.py) drains every
    # replica: no new admissions, in-flight decodes finish within
    # the grace, the router resumes the rest on siblings.
    for front in fronts:
        front.arm_preempt_drain(grace_s=args.drain_grace_s)

    def _shutdown():
        if router is not None:
            router.shutdown()
        for f in fronts:
            f.shutdown()

    if not args.loadgen:
        try:
            fronts[0]._http_thread.join()
        except KeyboardInterrupt:
            pass
        finally:
            _shutdown()
        return 0
    from batch_shipyard_tpu.models.loadgen import run_load
    # Engines were warmed before their fronts started, so jit
    # compilation never pollutes TTFT; one tiny request per front
    # still warms the HTTP dispatch path itself.
    for front in fronts:
        front.generate({"prompt": [1, 2, 3], "max_new_tokens": 2})
    report = run_load(
        url, args.loadgen, rate_hz=args.rate,
        prompt_len=tuple(args.prompt_len),
        max_new_tokens=tuple(args.gen_tokens),
        vocab_size=args.vocab, seed=args.seed,
        arrival=args.arrival,
        shared_prefix_groups=args.shared_prefix_groups,
        shared_prefix_len=args.shared_prefix_len,
        slo_classes=slo_classes)
    if router is not None:
        report["router"] = router.stats()
    prefix = [f.engine.prefix_stats() for f in fronts]
    if any(prefix):
        hits = sum(p["hit_tokens"] for p in prefix if p)
        total = sum(p["total_prompt_tokens"] for p in prefix if p)
        report["prefix_cache"] = {
            "hit_tokens": hits,
            "total_prompt_tokens": total,
            "hit_rate": hits / total if total else 0.0,
        }
    if args.speculative:
        spec = [f.engine.spec_stats() for f in fronts]
        proposed = sum(s["proposed"] for s in spec)
        accepted = sum(s["accepted"] for s in spec)
        report["speculative"] = {
            "gamma": args.gamma,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": (accepted / proposed
                                if proposed else 0.0),
        }
    _shutdown()
    with open(args.report, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps(report), flush=True)
    return 1 if report["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())

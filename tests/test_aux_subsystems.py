"""Monitoring (heimdall/file_sd + bundle), slurm burst, remotefs,
crypto, secrets, misc tests."""

import json
import os

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.monitor import heimdall, provision
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.remotefs import manager as remotefs
from batch_shipyard_tpu.slurm import burst
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
from batch_shipyard_tpu.utils import crypto, misc, secrets

GLOBAL = settings_mod.global_settings({})


def make_pool(store, substrate, pool_id="mp", accel="v5litepod-8"):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": accel},
        "max_wait_time_seconds": 30,
        "prometheus": {"node_exporter": {"enabled": True}}}}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return pool


# ------------------------------ monitoring -----------------------------

def test_heimdall_file_sd_targets(tmp_path):
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        make_pool(store, substrate)
        heimdall.add_pool_to_monitor(store, "mp",
                                     node_exporter_port=9100,
                                     cadvisor_port=8080)
        path = heimdall.write_file_sd(store, str(tmp_path))
        groups = json.loads(open(path).read())
        ne = [g for g in groups
              if g["labels"]["job"] == "node_exporter"][0]
        assert len(ne["targets"]) == 2  # v5e-8 = 2 workers
        assert all(t.endswith(":9100") for t in ne["targets"])
        ca = [g for g in groups if g["labels"]["job"] == "cadvisor"][0]
        assert all(t.endswith(":8080") for t in ca["targets"])
        # removal empties the target list
        heimdall.remove_resource_from_monitor(store, "pool$mp")
        heimdall.write_file_sd(store, str(tmp_path))
        assert json.loads(open(path).read()) == []
    finally:
        substrate.stop_all()


def test_monitoring_bundle_generation(tmp_path):
    out = provision.generate_monitoring_bundle(
        str(tmp_path / "mon"), grafana_password="s3cret")
    assert os.path.exists(os.path.join(out, "prometheus.yml"))
    compose = open(os.path.join(out, "docker-compose.yml")).read()
    assert "s3cret" in compose and "prom/prometheus" in compose
    dash = json.load(open(os.path.join(
        out, "grafana", "dashboards", "shipyard.json")))
    assert dash["panels"]
    assert os.path.exists(os.path.join(
        out, "shipyard-monitoring.service"))


# ------------------------------- slurm ---------------------------------

def test_hostlist_expansion():
    assert burst.expand_hostlist("tpu-[0-2,5]") == [
        "tpu-0", "tpu-1", "tpu-2", "tpu-5"]
    assert burst.expand_hostlist("a,b") == ["a", "b"]
    assert burst.expand_hostlist("single") == ["single"]


def test_slurm_resume_suspend_cycle():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        pool = make_pool(store, substrate, "sp", "v5litepod-4")
        hosts = ["tpu-0"]
        assignments = burst.process_resume(
            store, substrate, pool, "clus", "part", hosts,
            wait_timeout=30)
        assert set(assignments) == {"tpu-0"}
        # resume more hosts than capacity -> pool grows by slices
        assignments = burst.process_resume(
            store, substrate, pool, "clus", "part",
            ["tpu-0", "tpu-1"], wait_timeout=60)
        assert set(assignments) == {"tpu-0", "tpu-1"}
        assert len(pool_mgr.list_nodes(store, "sp")) >= 2
        released = burst.process_suspend(
            store, substrate, pool, "clus", "part", ["tpu-1"])
        assert released == 1
        assert set(burst.host_assignments(
            store, "clus", "part")) == {"tpu-0"}
    finally:
        substrate.stop_all()


def test_slurm_idle_reaper():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        pool = make_pool(store, substrate, "rp", "v5litepod-4")
        burst.process_resume(store, substrate, pool, "c", "p",
                             ["h0"], wait_timeout=30)
        # Nothing reaped inside the window.
        assert burst.idle_reaper(store, substrate, pool, "c", "p",
                                 idle_reclaim_seconds=3600) == 0
        import time
        assert burst.idle_reaper(
            store, substrate, pool, "c", "p",
            idle_reclaim_seconds=0.0, now=time.time() + 10) == 1
        assert burst.host_assignments(store, "c", "p") == {}
    finally:
        substrate.stop_all()


def test_slurm_conf_generation():
    conf = burst.generate_slurm_conf("clus", {
        "tpu": {"max_nodes": 4, "cpus": 96, "default": True}})
    assert "NodeName=tpu-[0-3] State=CLOUD" in conf
    assert "ResumeProgram=" in conf
    assert "PartitionName=tpu" in conf
    assert "SuspendTime=300" in conf  # default idle reclaim


def test_slurm_conf_idle_reclaim_and_unmanaged_partitions():
    """slurm_options.idle_reclaim_time_seconds -> SuspendTime;
    unmanaged_partitions pass through as static stanzas (reference
    unmanaged_partitions semantics)."""
    conf = burst.generate_slurm_conf(
        "clus", {"tpu": {"max_nodes": 2}},
        idle_reclaim_seconds=900,
        unmanaged_partitions=[{
            "partition": "onprem Nodes=static-[0-3] Default=NO "
                         "MaxTime=INFINITE State=UP",
            "nodes": ["NodeName=static-[0-3] CPUs=64 State=UNKNOWN"],
        }])
    assert "SuspendTime=900" in conf
    assert "NodeName=static-[0-3] CPUs=64 State=UNKNOWN" in conf
    assert "PartitionName=onprem Nodes=static-[0-3]" in conf


# ------------------------------ remotefs -------------------------------

def test_remotefs_record_and_mount_args():
    store = MemoryStateStore()
    remotefs.create_storage_cluster_record(store, "fs1", disk_count=4)
    with pytest.raises(ValueError):
        remotefs.create_storage_cluster_record(store, "fs1")
    with pytest.raises(ValueError):
        remotefs.create_storage_cluster_mount_args(store, "fs1")
    remotefs.register_server_node(store, "fs1", "srv0", "10.9.9.9")
    args = remotefs.create_storage_cluster_mount_args(store, "fs1")
    assert args[0].startswith("10.9.9.9:/export/shipyard ")
    assert "nfs4" in args[0]
    cluster = remotefs.expand_storage_cluster(store, "fs1", 2)
    assert cluster["disk_count"] == 6
    script = remotefs.generate_nfs_bootstrap_script(cluster)
    assert "mdadm --create" in script and "raid-devices=6" in script
    remotefs.delete_storage_cluster(store, "fs1")
    with pytest.raises(ValueError):
        remotefs.get_storage_cluster(store, "fs1")


def test_gcsfuse_mount_args():
    args = remotefs.gcsfuse_mount_args("my-bucket")
    assert args[0].startswith("my-bucket /mnt/gcs gcsfuse ")


# ------------------------------- crypto --------------------------------

@pytest.mark.skipif(not crypto.HAVE_CRYPTOGRAPHY,
                    reason="cryptography wheel absent from container")
def test_ssh_keypair_and_credential_roundtrip(tmp_path):
    private_path, public_path = crypto.generate_ssh_keypair(
        str(tmp_path))
    assert open(public_path).read().startswith("ssh-rsa ")
    assert oct(os.stat(private_path).st_mode & 0o777) == "0o600"
    private_pem, public_pem = crypto.generate_rsa_keypair_pem()
    token = crypto.encrypt_credential(public_pem, "hunter2")
    assert crypto.decrypt_credential(private_pem, token) == "hunter2"


def test_ssh_command_shape():
    argv = crypto.ssh_command("1.2.3.4", 2222, "me", "/key", "ls")
    assert argv[0] == "ssh" and argv[-1] == "ls"
    assert "me@1.2.3.4" in argv and "-i" in argv


# ------------------------------- secrets -------------------------------

def test_secret_env_and_file(tmp_path, monkeypatch):
    monkeypatch.setenv("MY_TOKEN", "tok123")
    assert secrets.resolve_secret("secret://env/MY_TOKEN") == "tok123"
    sf = tmp_path / "secrets.yaml"
    sf.write_text("regpass: hunter2\n")
    assert secrets.resolve_secret("secret://file/regpass",
                                  secrets_file=str(sf)) == "hunter2"
    config = {"credentials": {"docker_registries": [
        {"server": "r", "password": "secret://env/MY_TOKEN"}]}}
    resolved = secrets.resolve_config_secrets(config)
    assert resolved["credentials"]["docker_registries"][0][
        "password"] == "tok123"
    with pytest.raises(secrets.SecretResolutionError):
        secrets.resolve_secret("secret://env/NOPE")
    assert not secrets.is_secret_id("plain-value")


# -------------------------------- misc ---------------------------------

def test_tensorboard_tunnel_plan(tmp_path):
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        pool = make_pool(store, substrate, "tbp", "v5litepod-4")
        from batch_shipyard_tpu.jobs import manager as jobs_mgr
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "tbjob", "tasks": [{"command": "echo training"}]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        jobs_mgr.wait_for_tasks(store, "tbp", "tbjob", timeout=30)
        plan = misc.plan_tensorboard_tunnel(
            store, substrate, "tbp", "tbjob", "task-00000",
            output_dir=str(tmp_path))
        assert plan["local_url"] == "http://localhost:16006"
        assert "--logdir" in plan["remote_command"]
        assert os.path.exists(plan["tunnel_script"])
    finally:
        substrate.stop_all()


def test_mirror_images_plan():
    plan = misc.mirror_images_plan(["busybox:latest"], "my.registry")
    assert ["docker", "pull", "busybox:latest"] in plan
    assert ["docker", "push", "my.registry/busybox:latest"] in plan


def test_monitoring_bundle_with_lets_encrypt(tmp_path):
    out = provision.generate_monitoring_bundle(
        str(tmp_path / "tls"), lets_encrypt_fqdn="mon.example.com",
        lets_encrypt_staging=True)
    compose = open(os.path.join(out, "docker-compose.yml")).read()
    assert "nginx" in compose and "certbot" in compose
    assert "mon.example.com" in compose and "--staging" in compose
    nginx = open(os.path.join(out, "nginx.conf")).read()
    assert "mon.example.com" in nginx and "443 ssl" in nginx


def test_service_account_activation(tmp_path, monkeypatch):
    """utils/auth: key file -> ADC env + one-time gcloud activation;
    impersonation args only when email configured without a key
    (reference aad.py token machinery analog)."""
    from batch_shipyard_tpu.config.settings import (
        GcpCredentialsSettings)
    from batch_shipyard_tpu.utils import auth

    key = tmp_path / "sa.json"
    key.write_text("{}")
    calls = []

    def runner(argv, **_kw):
        calls.append(list(argv))
        return 0, "tok-abc\n", ""

    monkeypatch.delenv("GOOGLE_APPLICATION_CREDENTIALS",
                       raising=False)
    auth._activated.clear()
    gcp = GcpCredentialsSettings(
        project="p", zone=None,
        service_account_key_file=str(key),
        service_account_email=None)
    assert auth.ensure_service_account(gcp, runner=runner) is True
    assert os.environ["GOOGLE_APPLICATION_CREDENTIALS"] == str(key)
    assert calls == [["gcloud", "auth", "activate-service-account",
                      f"--key-file={key}"]]
    # Idempotent: second call does not re-activate.
    assert auth.ensure_service_account(gcp, runner=runner) is True
    assert len(calls) == 1
    # No key file -> ambient credentials, nothing run.
    assert auth.ensure_service_account(None, runner=runner) is False
    # Impersonation args: email without key only.
    imp = GcpCredentialsSettings(
        project="p", zone=None, service_account_key_file=None,
        service_account_email="svc@p.iam.gserviceaccount.com")
    assert auth.gcloud_impersonation_args(imp) == [
        "--impersonate-service-account="
        "svc@p.iam.gserviceaccount.com"]
    assert auth.gcloud_impersonation_args(gcp) == []
    assert auth.access_token(runner=runner) == "tok-abc"
    # Missing key file is a hard error.
    bad = GcpCredentialsSettings(
        project="p", zone=None,
        service_account_key_file=str(tmp_path / "nope.json"),
        service_account_email=None)
    with pytest.raises(FileNotFoundError):
        auth.ensure_service_account(bad, runner=runner)


def test_secret_store_file_roundtrip(tmp_path):
    """store_secret/resolve_secret round-trip through the file
    provider, atomically updating the YAML (keyvault add analog)."""
    from batch_shipyard_tpu.utils import secrets
    sfile = str(tmp_path / "secrets.yaml")
    secrets.store_secret("secret://file/apikey", "s3cr3t",
                         secrets_file=sfile)
    secrets.store_secret("secret://file/other", "v2",
                         secrets_file=sfile)
    assert secrets.resolve_secret("secret://file/apikey",
                                  secrets_file=sfile) == "s3cr3t"
    assert secrets.resolve_secret("secret://file/other",
                                  secrets_file=sfile) == "v2"
    import os
    mode = os.stat(sfile).st_mode & 0o777
    assert mode == 0o600, oct(mode)


def test_secret_store_env_readonly():
    from batch_shipyard_tpu.utils import secrets
    import pytest
    with pytest.raises(secrets.SecretResolutionError):
        secrets.store_secret("secret://env/NOPE", "x")


def test_store_and_fetch_credentials_config(tmp_path):
    """Whole-credentials-file storage round-trip (the reference keeps
    credentials.yaml in KeyVault, convoy/keyvault.py:71)."""
    from batch_shipyard_tpu.utils import secrets
    sfile = str(tmp_path / "secrets.yaml")
    creds = {"credentials": {"storage": {"backend": "localfs",
                                         "root": "/tmp/x"}}}
    secrets.store_credentials_config("secret://file/creds", creds,
                                     secrets_file=sfile)
    back = secrets.fetch_credentials_config("secret://file/creds",
                                            secrets_file=sfile)
    assert back == creds


def test_secret_store_gcp_uses_stdin(monkeypatch):
    """gcp_secret_manager writes the value via stdin, never argv."""
    from batch_shipyard_tpu.utils import secrets, util
    calls = []

    def fake_capture(cmd, **kwargs):
        calls.append((list(cmd), kwargs.get("stdin_data")))
        return 0, "", ""

    monkeypatch.setattr(util, "subprocess_capture", fake_capture)
    monkeypatch.setattr("shutil.which", lambda _n: "/usr/bin/gcloud")
    secrets.store_secret("secret://gcp_secret_manager/tok", "hush",
                         project="p")
    add_call = [c for c in calls if "add" in c[0]][0]
    assert add_call[1] == "hush"
    assert all("hush" not in arg for arg in add_call[0])


def test_cli_secrets_put_get(tmp_path):
    """The secrets CLI group end-to-end over the file provider."""
    import yaml
    from click.testing import CliRunner

    from batch_shipyard_tpu.cli.main import cli
    sfile = tmp_path / "secrets.yaml"
    confs = {"credentials": {"credentials": {
        "storage": {"backend": "localfs",
                    "root": str(tmp_path / "store")},
        "secrets": {"file": str(sfile)}}}}
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    runner = CliRunner()
    put = runner.invoke(cli, ["--configdir", str(tmp_path), "secrets",
                              "put", "secret://file/reg-password"],
                        input="hunter2\n")
    assert put.exit_code == 0, put.output
    got = runner.invoke(cli, ["--configdir", str(tmp_path), "secrets",
                              "get", "secret://file/reg-password"])
    assert got.exit_code == 0, got.output
    assert got.output.strip() == "hunter2"
    stored = runner.invoke(
        cli, ["--configdir", str(tmp_path), "secrets",
              "store-credentials", "secret://file/allcreds"])
    assert stored.exit_code == 0, stored.output
    fetched = runner.invoke(
        cli, ["--configdir", str(tmp_path), "secrets",
              "fetch-credentials", "secret://file/allcreds"])
    assert fetched.exit_code == 0, fetched.output
    assert "localfs" in fetched.output


def test_generic_port_tunnel_plan(tmp_path):
    """misc tunnel: ssh port-forward plan to any task service port
    (e.g. the serving front end from workloads/serve.py)."""
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        pool = make_pool(store, substrate, "svp", "v5litepod-4")
        from batch_shipyard_tpu.jobs import manager as jobs_mgr
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "svjob", "tasks": [{"command": "echo serving"}]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        jobs_mgr.wait_for_tasks(store, "svp", "svjob", timeout=30)
        plan = misc.plan_port_tunnel(
            store, substrate, "svp", "svjob", "task-00000",
            remote_port=8900, output_dir=str(tmp_path))
        assert plan["local_url"] == "http://localhost:8900"
        assert plan["remote_port"] == 8900
        assert os.path.exists(plan["tunnel_script"])
        script = open(plan["tunnel_script"]).read()
        assert "8900" in script
    finally:
        substrate.stop_all()

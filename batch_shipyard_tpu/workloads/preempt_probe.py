"""Preempt-probe: a featherweight preempt-aware "trainer" for drills.

The preemption drill's acceptance criterion is about the CONTROL
plane — notice delivered, drain to a step boundary, COMMITTED
checkpoint forced, distinct preempted exit, resume with zero lost
steps — not about matmuls. A real train workload would spend seconds
importing jax/orbax per gang instance per attempt; this probe speaks
the exact same contracts with stdlib-only imports:

  * progress beats ($SHIPYARD_PROGRESS_FILE, agent/progress.py)
  * goodput step windows ($SHIPYARD_GOODPUT_FILE, goodput/events.py)
  * preempt requests ($SHIPYARD_PREEMPT_REQUEST_FILE,
    agent/preemption.PreemptWatcher)
  * the COMMITTED-marker checkpoint protocol (a JSON state file +
    sibling marker, atomic tmp+rename — workloads/checkpoint.py's
    commit discipline without the Orbax payload)

Step ledger: every attempt appends the step numbers it actually
executed to ``<ckpt>.steps.log`` — the drill's zero-lost-steps
assertion reads it (each step executed exactly once across attempts
when the drain committed the barrier).

Usage (drill/gang task command):
    python -m batch_shipyard_tpu.workloads.preempt_probe \
        --steps 40 --step-seconds 0.05 --ckpt /path/state.json
"""

from __future__ import annotations

import argparse
import json
import os
import time

from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.agent import progress
from batch_shipyard_tpu.goodput import events as goodput_events


def _restore(ckpt: str) -> int:
    """Committed step, honoring the marker protocol: state without a
    sibling .COMMITTED marker is a torn save and restores as 0."""
    if not (ckpt and os.path.exists(ckpt)
            and os.path.exists(ckpt + ".COMMITTED")):
        return 0
    try:
        with open(ckpt, encoding="utf-8") as fh:
            return int(json.load(fh).get("step", 0))
    except (OSError, ValueError):
        return 0


def _commit(ckpt: str, step: int) -> None:
    """state -> fsync'd tmp -> rename -> marker (the checkpoint.py
    commit order: a crash at any point leaves the previous committed
    state or an unmarked torn file, never a torn pickup)."""
    os.makedirs(os.path.dirname(ckpt) or ".", exist_ok=True)
    tmp = ckpt + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"step": step}))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, ckpt)
    marker_tmp = ckpt + ".COMMITTED.tmp"
    with open(marker_tmp, "w", encoding="utf-8") as fh:
        fh.write(str(step))
    os.replace(marker_tmp, ckpt + ".COMMITTED")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--step-seconds", type=float, default=0.05)
    parser.add_argument("--ckpt", required=True,
                        help="shared state file (job scratch/shared "
                             "dir); instance 0 is the single writer")
    parser.add_argument("--checkpoint-every", type=int, default=0,
                        help="cadenced commits every N steps (the "
                             "preempt drain commits regardless)")
    parser.add_argument("--cache-identity",
                        default=os.environ.get(
                            "SHIPYARD_COMPILE_CACHE_IDENTITY"),
                        help="compile-cache identity advertised via "
                             "sched hints (victim-cost pricing reads "
                             "it from the task row)")
    parser.add_argument("--ignore-notice", action="store_true",
                        help="UNCOOPERATIVE victim mode (eviction "
                             "drills): observe the preempt request, "
                             "log it to the ledger, and keep "
                             "stepping — the sweep's post-grace "
                             "hard kill is the only way off the "
                             "node, exactly the workload shape "
                             "forcible eviction exists for")
    args = parser.parse_args()

    instance = int(os.environ.get("SHIPYARD_TASK_INSTANCE", "0"))
    writer = instance == 0
    start_step = _restore(args.ckpt)
    # Advertise scheduling hints up front: the agent mirrors the hints
    # file into the task row on heartbeats, and the preempt sweep's
    # victim-cost pricing (sched/policy.py victim_cost_from_row) reads
    # them — a victim with a committed checkpoint + warm cache identity
    # is cheap to kill, one without is expensive.
    progress.record_sched_hints(
        step=start_step, ckpt_step=start_step,
        step_seconds=args.step_seconds,
        cache_identity=args.cache_identity)
    watcher = preemption.PreemptWatcher()
    window_started = time.time()
    executed: list[int] = []

    def _flush_window(end_step: int) -> None:
        if executed:
            goodput_events.record(
                goodput_events.PROGRAM_STEP_WINDOW, window_started,
                time.time(), step_start=executed[0],
                step_end=end_step, tokens=len(executed))

    ignoring = False
    for step in range(start_step, args.steps):
        time.sleep(args.step_seconds)
        progress.beat()
        executed.append(step)
        done = step + 1
        progress.record_sched_hints(step=done)
        if watcher.poll() is not None:
            if args.ignore_notice:
                # The uncooperative shape eviction exists for: a
                # victim that neither drains NOR commits once
                # noticed (a healthy cadenced committer would have
                # drained cooperatively) — it squats on the slot,
                # still stepping/beating, until the escalation hard
                # kill. Acknowledge the notice in the ledger so the
                # drill can assert the resume barrier is strictly
                # PRE-notice, then stop committing.
                ignoring = True
                if writer:
                    with open(args.ckpt + ".steps.log", "a",
                              encoding="utf-8") as fh:
                        fh.write(f"i{instance} "
                                 f"{executed[0]}..{done} "
                                 f"notice-ignored\n")
                continue
            # Drain: this boundary is the barrier — commit, ledger,
            # distinct preempted exit. Non-writers exit on the same
            # boundary without touching the shared state (the
            # single-writer convention real save pipelines follow).
            if writer:
                _commit(args.ckpt, done)
                progress.record_sched_hints(ckpt_step=done)
                with open(args.ckpt + ".steps.log", "a",
                          encoding="utf-8") as fh:
                    fh.write(f"i{instance} {executed[0]}..{done} "
                             f"preempted\n")
            _flush_window(done)
            return preemption.EXIT_PREEMPTED
        if writer and not ignoring and args.checkpoint_every and \
                done % args.checkpoint_every == 0:
            _commit(args.ckpt, done)
            progress.record_sched_hints(ckpt_step=done)
    if writer:
        _commit(args.ckpt, args.steps)
        with open(args.ckpt + ".steps.log", "a",
                  encoding="utf-8") as fh:
            fh.write(f"i{instance} {start_step}..{args.steps} "
                     f"completed\n")
    _flush_window(args.steps)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""ResNet-50 training payload: the TensorFlow-Distributed recipe's
workload (ResNet-50/ImageNet shapes), TPU-native.

Runs single-chip or as a gang task across a pod slice (data parallel
over all global devices); synthetic data by default, or a directory of
.npy shards staged via input_data.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.train_resnet \
        --batch-per-device 128 --steps 50
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.models import resnet as resnet_mod
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-per-device", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--data-dir", default=None,
                        help=".npy/.npz shard directory (staged via "
                             "input_data or a gcsfuse mount); "
                             "synthetic data when omitted")
    parser.add_argument("--prefetch", type=int, default=2)
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    batch_size = args.batch_per_device * n_dev
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = resnet_mod.ResNetConfig(num_classes=args.num_classes,
                                     dtype=jnp.bfloat16)
    harness = train_mod.build_resnet_train(
        mesh, config, batch_size=batch_size,
        image_size=args.image_size)
    from batch_shipyard_tpu.data import loader

    rng = np.random.RandomState(jax.process_index())
    if args.data_dir:
        dataset = loader.ShardedDataset(args.data_dir, batch_size)
        # Transfer compact uint8 and normalize ON DEVICE: host-side
        # float conversion made the pipeline the bottleneck (~4x
        # fewer bytes over PCIe and the VPU does the cast for free).
        normalize = jax.jit(
            lambda img: (img.astype(jnp.float32) / 127.5 - 1.0
                         ).astype(jnp.bfloat16),
            out_shardings=harness.batch_sharding)
        raw = loader.prefetch_to_device(iter(dataset),
                                        harness.batch_sharding,
                                        depth=args.prefetch)
        batches = ({"images": normalize(b["images"]),
                    "labels": b["labels"].astype(jnp.int32)}
                   for b in raw)
    else:
        synthetic = {
            "images": jnp.asarray(
                rng.randn(batch_size, args.image_size,
                          args.image_size, 3), jnp.bfloat16),
            "labels": jnp.asarray(
                rng.randint(0, args.num_classes, (batch_size,)),
                jnp.int32),
        }
        batches = loader.synthetic_batches(lambda step: synthetic)
    params, opt_state = harness.params, harness.opt_state
    for _ in range(args.warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  next(batches))
    float(metrics["loss"])  # hard sync
    start = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  next(batches))
    loss = float(metrics["loss"])
    elapsed = time.perf_counter() - start
    images_per_sec = batch_size * args.steps / elapsed
    distributed.log(ctx, (
        f"resnet50: {images_per_sec:.1f} img/s total, "
        f"{images_per_sec / n_dev:.1f} img/s/chip, "
        f"loss={loss:.4f}, {elapsed / args.steps * 1000:.1f} ms/step"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

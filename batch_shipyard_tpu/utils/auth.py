"""Service-account credential handling for daemons and headless VMs.

Reference analog: convoy/aad.py (device code / service principal /
MSI token machinery). The GCP redesign needs far less: interactive
use inherits gcloud's ambient user credentials, and headless daemons
(federation proxy VM, monitoring VM, slurm controller) authenticate
as a service account via its key file — this module makes that one
call idempotent and applies it to BOTH auth paths the framework uses:

  - Application Default Credentials (google-cloud-storage's GCS
    client): GOOGLE_APPLICATION_CREDENTIALS points at the key file;
  - the gcloud CLI (every substrate/provisioning call): the service
    account is activated once per process, after which all gcloud
    invocations run as it.

Impersonation (`service_account_email` without a key file) is exposed
as per-call gcloud args for operators who prefer short-lived tokens
over key distribution.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_lock = threading.Lock()
_activated: set[str] = set()


def ensure_service_account(gcp, runner=None) -> bool:
    """Apply the configured service account (idempotent per key file).

    Sets GOOGLE_APPLICATION_CREDENTIALS for ADC consumers and runs
    `gcloud auth activate-service-account` so CLI-driven paths use the
    same identity. Returns True if a service account is active, False
    when no key file is configured (ambient credentials)."""
    key_file = getattr(gcp, "service_account_key_file", None) \
        if gcp is not None else None
    if not key_file:
        return False
    if not os.path.exists(key_file):
        raise FileNotFoundError(
            f"service_account_key_file does not exist: {key_file}")
    with _lock:
        # Plain assignment: ADC and gcloud must agree on the identity
        # (a leftover GOOGLE_APPLICATION_CREDENTIALS, or a second key
        # file in the same process, would otherwise split them).
        os.environ["GOOGLE_APPLICATION_CREDENTIALS"] = key_file
        if key_file in _activated:
            return True
        run = runner or util.subprocess_capture
        rc, _out, err = run([
            "gcloud", "auth", "activate-service-account",
            f"--key-file={key_file}"])
        if rc != 0:
            raise RuntimeError(
                f"service account activation failed: {err.strip()}")
        _activated.add(key_file)
        logger.info("activated service account from %s", key_file)
        return True


def gcloud_impersonation_args(gcp) -> list[str]:
    """Per-call gcloud args for impersonation (email configured, no
    key file): short-lived tokens minted by the caller's ambient
    identity instead of a distributed key."""
    email = getattr(gcp, "service_account_email", None) \
        if gcp is not None else None
    key_file = getattr(gcp, "service_account_key_file", None) \
        if gcp is not None else None
    if email and not key_file:
        return [f"--impersonate-service-account={email}"]
    return []


def access_token(runner=None) -> str:
    """Mint an access token for raw HTTP callers (the aad.py
    get_token analog) using whatever identity is active."""
    run = runner or util.subprocess_capture
    rc, out, err = run(["gcloud", "auth", "print-access-token"])
    if rc != 0:
        raise RuntimeError(f"token mint failed: {err.strip()}")
    return out.strip()

"""Serving-engine invariant rules.

The paged KV pool (models/serving.py) runs a page lifecycle —
FREE -> OWNED -> PINNED (prefix-indexed, refcounted) -> LRU -> FREE —
whose accounting invariant (`_avail_pages` = total - pinned -
reservations) every admission decision trusts. The single release
helper (`_release_pages`) is the only place a page may legally return
to the free list, because it is the only code that also settles the
refcount, the LRU membership, and the availability counter. A direct
`_free_pages` mutation anywhere else frees a page without that
settlement: the page can be handed to a new request while a shared
prefix still references it — silent KV corruption that decodes
plausible-but-wrong tokens.
"""

from __future__ import annotations

import ast

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, rule)

# The only functions allowed to touch the free list directly:
# construction seeds it, the allocator pops from it, and the release
# helper returns pages to it (settling refcounts/LRU/avail as it
# does).
_ALLOWED_FUNCS = {"__init__", "_alloc_page", "_release_pages"}

# list-mutating method calls on the attribute.
_MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop",
                     "clear", "sort", "reverse"}

_ATTR = "_free_pages"


def _is_free_pages_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == _ATTR


def _mutation(node: ast.AST) -> bool:
    """True when ``node`` mutates a ``*._free_pages`` attribute:
    a mutating method call, a (re)assignment or item assignment, an
    augmented assignment, or a del."""
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATING_METHODS and \
            _is_free_pages_attr(node.func.value):
        return True
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for target in targets:
            if _is_free_pages_attr(target):
                return True
            if isinstance(target, ast.Subscript) and \
                    _is_free_pages_attr(target.value):
                return True
    if isinstance(node, ast.Delete):
        for target in node.targets:
            if _is_free_pages_attr(target) or (
                    isinstance(target, ast.Subscript) and
                    _is_free_pages_attr(target.value)):
                return True
    return False


def _walk_functions(tree: ast.AST):
    """Yield (enclosing_function_name, node) for every node, where
    the name is the innermost def/async def ('' at module level)."""

    def visit(node, func_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                yield from visit(child, child.name)
            else:
                yield func_name, child
                yield from visit(child, func_name)

    yield from visit(tree, "")


@rule("serving-page-refcount", family="serving")
def check_serving_page_refcount(ctx: AnalysisContext) -> list[Finding]:
    """A direct mutation of ``*._free_pages`` (append/extend/pop/
    assignment/del/...) outside ``__init__``/``_alloc_page``/
    ``_release_pages``: freeing or reassigning KV pool pages must go
    through the single release helper, which also settles the prefix
    refcount, LRU membership, and the ``_avail_pages`` accounting.
    A bare free-list write skips that settlement, so a page still
    referenced by a cached prefix can be reissued to a new request —
    the decode then gathers another request's KV rows and emits
    plausible-but-wrong tokens with no crash to flag it.

    Provenance: the first draft of slot teardown returned pages with
    ``self._free_pages.extend(self._slot_pages[i])`` directly — exactly
    right before prefix sharing existed, silently corrupting once a
    page could be pinned by the prefix index with refcount > 0. The
    shared-prefix churn test (tests/test_prefix_cache.py) only catches
    the shapes it generates; this rule closes the class."""
    findings = []
    for src in ctx.python_files:
        for func_name, node in _walk_functions(src.tree):
            if func_name in _ALLOWED_FUNCS:
                continue
            if _mutation(node):
                findings.append(Finding(
                    rule="serving-page-refcount", path=src.rel,
                    line=node.lineno,
                    message=(f"direct _free_pages mutation in "
                             f"{func_name or '<module>'}(); page "
                             f"frees must go through _release_pages "
                             f"(it settles refcounts, LRU membership "
                             f"and _avail_pages — a bare free-list "
                             f"write can reissue a page a cached "
                             f"prefix still references)")))
    return findings


def _is_admission_call(node: ast.AST) -> bool:
    """A call that admits work into a ContinuousBatcher: the engine's
    slot-admission hook firing (``*.on_admit(...)``) or a front end
    enqueueing into the engine (``*.engine.submit(...)``)."""
    if not (isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute)):
        return False
    if node.func.attr == "on_admit":
        return True
    return (node.func.attr == "submit" and
            isinstance(node.func.value, ast.Attribute) and
            node.func.value.attr == "engine")


@rule("serving-drain-no-admit", family="serving")
def check_serving_drain_no_admit(ctx: AnalysisContext
                                 ) -> list[Finding]:
    """A function that admits work into the ContinuousBatcher —
    firing the slot-admission hook (``on_admit``) or submitting into
    the engine (``*.engine.submit``) — without consulting the
    ``draining`` flag anywhere in its body. The drain ladder's whole
    guarantee is 'no admissions after the preempt/evict notice':
    every admission path must check ``draining`` before seating work,
    or a draining replica keeps accepting decodes the router already
    failed over — the same request then decodes on two replicas and
    the exactly-once stream contract breaks.

    Provenance: the drain feature landed with the check in
    ``_admit``; a later admission path (chunked-prefill fast path,
    a new batch front door) that forgets the flag would pass every
    drain test that doesn't exercise that specific path. This rule
    closes the class structurally."""
    findings = []
    for src in ctx.python_files:
        # Group nodes by enclosing function, then require any
        # admitting function to also reference ``draining``.
        by_func: dict[str, list[ast.AST]] = {}
        for func_name, node in _walk_functions(src.tree):
            by_func.setdefault(func_name, []).append(node)
        for func_name, nodes in by_func.items():
            admissions = [n for n in nodes if _is_admission_call(n)]
            if not admissions:
                continue
            checks_drain = any(
                (isinstance(n, ast.Attribute) and
                 n.attr == "draining") or
                (isinstance(n, ast.Name) and n.id == "draining")
                for n in nodes)
            if checks_drain:
                continue
            for call in admissions:
                findings.append(Finding(
                    rule="serving-drain-no-admit", path=src.rel,
                    line=call.lineno,
                    message=(f"{func_name or '<module>'}() admits "
                             f"into the ContinuousBatcher without "
                             f"checking the draining flag; every "
                             f"admission path must refuse work once "
                             f"drain starts, or a draining replica "
                             f"seats decodes the router already "
                             f"resumed elsewhere (double decode, "
                             f"broken exactly-once stream)")))
    return findings

"""Federation proxy VM provisioning.

Reference analog: convoy/federation.py (provisions the federation
proxy VM running the docker-composed federation daemon) +
scripts/shipyard_federation_bootstrap.sh. Ours provisions a GCE VM
(substrate/gce_vm.py) whose startup script installs the framework +
store credentials and runs `shipyard-tpu fed proxy` under systemd —
the HA story is N replicas of this VM (the processor's store lease
elects the active one).
"""

from __future__ import annotations

from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_SYSTEMD_UNIT = """\
[Unit]
Description=batch-shipyard-tpu federation processor
After=network-online.target

[Service]
ExecStart=/usr/bin/python3 -m batch_shipyard_tpu.cli.main \\
  --configdir {configdir} fed proxy
Restart=always
RestartSec=5

[Install]
WantedBy=multi-user.target
"""


def generate_proxy_bootstrap(
        federation_id: str,
        configdir: str = "/opt/shipyard/config",
        package_source: str = "batch-shipyard-tpu",
        store_config_yaml: Optional[str] = None) -> str:
    """First-boot script for the proxy VM (the
    shipyard_federation_bootstrap.sh role)."""
    from batch_shipyard_tpu.slurm.provision import (
        _framework_install_script)
    framework = _framework_install_script(package_source, configdir,
                                          store_config_yaml)
    unit = _SYSTEMD_UNIT.format(configdir=configdir)
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu federation proxy bootstrap ({federation_id})
apt-get update
apt-get install -y python3-pip
mkdir -p /opt/shipyard
{framework}
cat > /etc/systemd/system/shipyard-fed-proxy.service <<'SHIPYARD_EOF'
{unit}SHIPYARD_EOF
systemctl daemon-reload
systemctl enable --now shipyard-fed-proxy.service
"""


def provision_proxy_vm(store: StateStore, federation_id: str,
                       project: str, zone: Optional[str] = None,
                       network: Optional[str] = None,
                       vm_size: str = "e2-standard-2",
                       replica: int = 0,
                       package_source: str = "batch-shipyard-tpu",
                       store_config_yaml: Optional[str] = None,
                       public_ip: bool = True,
                       vms=None) -> str:
    """Create a proxy VM replica; returns its internal IP. Run more
    than one replica for HA — the store lease serializes them."""
    from batch_shipyard_tpu.federation.federation import get_federation
    get_federation(store, federation_id)  # raises on unknown id
    if vms is None:
        from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
        vms = GceVmManager(project, zone=zone, network=network)
    name = f"shipyard-fed-{federation_id}-proxy{replica}"
    ip = vms.create_vm(
        name, vm_size, public_ip=public_ip,
        startup_script=generate_proxy_bootstrap(
            federation_id, package_source=package_source,
            store_config_yaml=store_config_yaml),
        tags=("shipyard-federation",))
    store.upsert_entity(names.TABLE_FEDERATIONS, "proxies",
                        name, {
        "federation_id": federation_id, "internal_ip": ip,
        "state": "running",
        "created_at": util.datetime_utcnow_iso(),
    })
    logger.info("federation proxy %s provisioned at %s", name, ip)
    return ip


def destroy_proxy_vms(store: StateStore, federation_id: str,
                      project: str, zone: Optional[str] = None,
                      vms=None) -> int:
    """Delete every registered proxy replica for a federation."""
    if vms is None:
        from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
        vms = GceVmManager(project, zone=zone)
    count = 0
    for row in list(store.query_entities(names.TABLE_FEDERATIONS,
                                         partition_key="proxies")):
        if row.get("federation_id") != federation_id:
            continue
        try:
            vms.delete_vm(row["_rk"])
        except Exception as exc:  # noqa: BLE001
            if "not found" in str(exc).lower():
                # Deleted out-of-band: the record is stale, clear it.
                logger.info("proxy VM %s already gone", row["_rk"])
            else:
                # Keep the record (so a retry can find it) and keep
                # going — one bad replica must not block the rest.
                logger.exception("failed to delete proxy VM %s",
                                 row["_rk"])
                continue
        try:
            store.delete_entity(names.TABLE_FEDERATIONS, "proxies",
                                row["_rk"])
        except NotFoundError:
            pass
        count += 1
    return count


def _proxy_rows(store: StateStore, federation_id: str) -> list[dict]:
    rows = [row for row in store.query_entities(
        names.TABLE_FEDERATIONS, partition_key="proxies")
        if row.get("federation_id") == federation_id]
    if not rows:
        raise ValueError(
            f"no proxy VMs registered for federation {federation_id}")
    return sorted(rows, key=lambda r: r["_rk"])


def _proxy_vms(project, zone, vms):
    from batch_shipyard_tpu.utils import service_vm
    return service_vm.default_vms(project, zone, vms)


def proxy_vm_status(store: StateStore, federation_id: str,
                    project: Optional[str] = None,
                    zone: Optional[str] = None,
                    vms=None) -> list[dict]:
    """Stored record + live status per proxy replica (reference
    `fed proxy status`, shipyard.py:2573+)."""
    from batch_shipyard_tpu.utils import service_vm
    vms = _proxy_vms(project, zone, vms)
    return [service_vm.vm_status(vms, row["_rk"], row)
            for row in _proxy_rows(store, federation_id)]


def suspend_proxy_vms(store: StateStore, federation_id: str,
                      project: Optional[str] = None,
                      zone: Optional[str] = None,
                      replica: Optional[int] = None,
                      vms=None) -> int:
    """Stop proxy replica(s) in place (reference `fed proxy
    suspend`). replica=None suspends every replica."""
    from batch_shipyard_tpu.utils import service_vm
    vms = _proxy_vms(project, zone, vms)
    count = 0
    for row in _proxy_rows(store, federation_id):
        if replica is not None and not row["_rk"].endswith(
                f"proxy{replica}"):
            continue
        service_vm.suspend_vm(vms, row["_rk"], store,
                              names.TABLE_FEDERATIONS, "proxies")
        count += 1
    return count


def start_proxy_vms(store: StateStore, federation_id: str,
                    project: Optional[str] = None,
                    zone: Optional[str] = None,
                    replica: Optional[int] = None,
                    vms=None) -> int:
    """Restart suspended proxy replica(s) (reference `fed proxy
    start`)."""
    from batch_shipyard_tpu.utils import service_vm
    vms = _proxy_vms(project, zone, vms)
    count = 0
    for row in _proxy_rows(store, federation_id):
        if replica is not None and not row["_rk"].endswith(
                f"proxy{replica}"):
            continue
        service_vm.start_vm(vms, row["_rk"], store,
                            names.TABLE_FEDERATIONS, "proxies")
        count += 1
    return count


def proxy_vm_ssh_argv(store: StateStore, federation_id: str,
                      replica: int = 0,
                      username: Optional[str] = None,
                      ssh_private_key: Optional[str] = None,
                      command: Optional[str] = None) -> list[str]:
    """ssh argv to one proxy replica (reference `fed proxy ssh`)."""
    from batch_shipyard_tpu.utils import service_vm
    suffix = f"proxy{replica}"
    for row in _proxy_rows(store, federation_id):
        if row["_rk"].endswith(suffix):
            return service_vm.ssh_argv(row["internal_ip"], username,
                                       ssh_private_key, command)
    raise ValueError(
        f"federation {federation_id} has no replica {replica}")

"""Goodput/trace-partition registry rules.

The goodput partition (arxiv 2502.06982) is exact only if every
emitted event kind is declared, registered, and priced: an undeclared
kind is silently dropped at emit (events.emit guards on EVENT_KINDS),
an unpriced interval kind lands in "unaccounted", and an unclosed
span never reaches the exporter at all. Same story for tables, state
vocabularies, and trace spans — these rules absorb and generalize
the AST checks that lived in tests/test_names_consistency.py (that
file is now a thin wrapper running them).
"""

from __future__ import annotations

import ast
from typing import Optional

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, const_str, rule)
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as gp_events
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.trace import spans as trace_spans

_TABLE_METHODS = {
    "insert_entity", "upsert_entity", "merge_entity", "get_entity",
    "query_entities", "delete_entity", "insert_entities",
}
_DECLARED_TABLE_ATTRS = {a for a in dir(names)
                         if a.startswith("TABLE_")}
_DECLARED_TABLE_VALUES = {getattr(names, a)
                          for a in _DECLARED_TABLE_ATTRS}

# Instantaneous marker kinds: zero-duration by contract, so the
# accounting sweep ignores them — every OTHER registered kind must be
# priced by _KIND_CATEGORY or the partition silently leaks seconds
# into "unaccounted". Extending this set is a reviewed statement that
# a kind is a marker, not an interval.
MARKER_EVENT_KINDS = frozenset({
    gp_events.TASK_RETRY, gp_events.TASK_PREEMPT_NOTICE,
    gp_events.TASK_PREEMPT_EXIT, gp_events.TASK_EVICTED,
    gp_events.GANG_RESIZE,
})

_EVENTS_MODULE = "batch_shipyard_tpu.goodput.events"
_SPANS_MODULE = "batch_shipyard_tpu.trace.spans"


def _module_aliases(tree: ast.AST, module: str) -> set[str]:
    """Local names bound to ``module`` in this file, via
    ``from pkg import events [as alias]`` or ``import pkg.mod``."""
    pkg, _, mod = module.rpartition(".")
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == pkg:
                for alias in node.names:
                    if alias.name == mod:
                        aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == module and alias.asname:
                    aliases.add(alias.asname)
    return aliases


def _check_registry_attrs(ctx: AnalysisContext, rule_id: str,
                          module: str, registry_obj,
                          kind_set: frozenset,
                          kind_label: str) -> list[Finding]:
    """Every UPPER_CASE attribute referenced on an alias of
    ``module`` must exist there, and (unless it is an *_ENV constant
    or the registry set itself) its value must be registered in
    ``kind_set``."""
    findings = []
    for src in ctx.python_files:
        aliases = _module_aliases(src.tree, module)
        if not aliases:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases):
                continue
            attr = node.attr
            if not attr.isupper() or attr.endswith("_ENV") or \
                    attr in ("EVENT_KINDS", "SPAN_KINDS"):
                continue
            value = getattr(registry_obj, attr, None)
            if value is None:
                findings.append(Finding(
                    rule=rule_id, path=src.rel, line=node.lineno,
                    message=(f"{attr} is not declared in "
                             f"{module}")))
            elif isinstance(value, str) and value not in kind_set:
                findings.append(Finding(
                    rule=rule_id, path=src.rel, line=node.lineno,
                    message=(f"{attr} value {value!r} is not "
                             f"registered in {kind_label}")))
    return findings


@rule("registry-table-undeclared", family="registry")
def check_table_undeclared(ctx: AnalysisContext) -> list[Finding]:
    """Every state-store table the package touches must be declared
    in state/names.py — whether referenced as names.TABLE_X, as a
    string literal in a store call, or through a module-level
    constant (_SCHED_TABLE = "..."). A typo-forked table name splits
    the schema into a partition nobody reads.

    Provenance: the original test_names_consistency check (PR 2),
    extended here to resolve local constants — which immediately
    caught jobs/schedules.py's hand-rolled "jobschedules" literal
    (now names.TABLE_JOBSCHEDULES)."""
    findings = []
    for src in ctx.python_files:
        # Module constants: NAME = "literal" assignments.
        consts: dict[str, str] = {}
        for node in ast.iter_child_nodes(src.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        consts[target.id] = node.value.value
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("TABLE_") and \
                    node.attr not in _DECLARED_TABLE_ATTRS:
                findings.append(Finding(
                    rule="registry-table-undeclared", path=src.rel,
                    line=node.lineno,
                    message=(f"{node.attr} is not declared in "
                             f"state/names.py")))
            if isinstance(node, ast.Call) and \
                    call_name(node) in _TABLE_METHODS and node.args:
                first = node.args[0]
                value: Optional[str] = const_str(first)
                if value is None and isinstance(first, ast.Name):
                    value = consts.get(first.id)
                if value is not None and \
                        value not in _DECLARED_TABLE_VALUES:
                    findings.append(Finding(
                        rule="registry-table-undeclared",
                        path=src.rel, line=node.lineno,
                        message=(f"table name {value!r} is not a "
                                 f"declared state/names.py TABLE_* "
                                 f"value")))
    return findings


@rule("registry-state-literal", family="registry")
def check_state_literal(ctx: AnalysisContext) -> list[Finding]:
    """Every task/node/aux state string literal compared against or
    written into an entity's "state" must come from the
    state/names.py vocabularies — a typo'd state ("quarantine" vs
    "quarantined") silently dodges every terminal-state check in the
    fleet.

    Provenance: the PR 5 quarantined-state review (the original
    test_names_consistency scan, migrated verbatim)."""
    allowed = (set(names.TASK_STATES) | set(names.NODE_STATES)
               | set(names.AUX_STATES))
    findings = []
    for src in ctx.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if const_str(key) == "state" and \
                            const_str(value) is not None and \
                            value.value not in allowed:
                        findings.append(Finding(
                            rule="registry-state-literal",
                            path=src.rel, line=node.lineno,
                            message=(f"state literal "
                                     f"{value.value!r} not in "
                                     f"state/names.py "
                                     f"vocabularies")))
            if isinstance(node, ast.Compare):
                if "state" not in ast.dump(node.left).lower():
                    continue
                for comparator in node.comparators:
                    literals = []
                    if const_str(comparator) is not None:
                        literals = [comparator.value]
                    elif isinstance(comparator, (ast.Tuple, ast.List,
                                                 ast.Set)):
                        literals = [
                            e.value for e in comparator.elts
                            if const_str(e) is not None]
                    for literal in literals:
                        # Upper-case literals are cloud-API enums
                        # (GCE VM states), not our vocabulary.
                        if literal and literal not in allowed and \
                                literal.isidentifier() and \
                                literal == literal.lower():
                            findings.append(Finding(
                                rule="registry-state-literal",
                                path=src.rel, line=node.lineno,
                                message=(f"state literal "
                                         f"{literal!r} not in "
                                         f"state/names.py "
                                         f"vocabularies")))
    return findings


@rule("goodput-kind-undeclared", family="registry")
def check_goodput_kind_undeclared(ctx: AnalysisContext,
                                  ) -> list[Finding]:
    """Every event-kind constant referenced through a goodput/events
    alias must be declared there AND registered in EVENT_KINDS: emit
    drops unknown kinds with only a log line, so a typo'd constant
    produces events the accounting never sees.

    Provenance: the PR 2 PROGRAM_* scan plus the PR 5/PR 10
    TASK_BACKOFF / TASK_PREEMPT_* extensions, generalized from
    hand-listed attribute sets to every reference."""
    return _check_registry_attrs(
        ctx, "goodput-kind-undeclared", _EVENTS_MODULE, gp_events,
        gp_events.EVENT_KINDS, "goodput EVENT_KINDS")


@rule("goodput-kind-unpriced", family="registry")
def check_goodput_kind_unpriced(ctx: AnalysisContext) -> list[Finding]:
    """Every registered event kind must be priced by the accounting
    sweep (_KIND_CATEGORY) or be a declared instantaneous marker
    (MARKER_EVENT_KINDS): an unpriced interval kind's seconds fall
    into "unaccounted" and the goodput partition stops meaning
    anything.

    Provenance: the PR 5 TASK_BACKOFF review — the event existed
    for a full review round before it was priced, and only the
    partition-exactness assertion in a drill caught it. Anchored to
    the EVENT_KINDS declaration in goodput/events.py."""
    findings = []
    src = ctx.get("batch_shipyard_tpu/goodput/events.py")
    if src is None:
        return findings
    # Anchor findings at the EVENT_KINDS declaration.
    line = 1
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
                for t in node.targets):
            line = node.lineno
            break
    priced = set(accounting._KIND_CATEGORY) | set(MARKER_EVENT_KINDS)
    for kind in sorted(gp_events.EVENT_KINDS):
        if kind not in priced:
            findings.append(Finding(
                rule="goodput-kind-unpriced", path=src.rel, line=line,
                message=(f"event kind {kind!r} is registered but "
                         f"neither priced by accounting."
                         f"_KIND_CATEGORY nor declared an "
                         f"instantaneous marker")))
    return findings


@rule("trace-span-undeclared", family="registry")
def check_span_undeclared(ctx: AnalysisContext) -> list[Finding]:
    """Every span-kind constant referenced through a trace/spans
    alias must be declared there AND registered in SPAN_KINDS — an
    unknown kind is dropped at emit, so the exporter's parent-link
    tree silently loses a node.

    Provenance: the PR 7 SPAN_* scan from test_names_consistency,
    generalized to every aliased reference."""
    return _check_registry_attrs(
        ctx, "trace-span-undeclared", _SPANS_MODULE, trace_spans,
        trace_spans.SPAN_KINDS, "trace SPAN_KINDS")


@rule("trace-span-no-with", family="registry")
def check_span_no_with(ctx: AnalysisContext) -> list[Finding]:
    """goodput.span / trace span / phase are context managers: called
    as a bare statement the interval is OPENED (generator created)
    but never closed — nothing is emitted, no exception, just a
    missing row. The open must have a reachable close, which the
    ``with`` statement guarantees (emit lives in its finally).

    Provenance: the PR 7 serve-span review, where a bare
    spans.phase(...) call in a prototype recorded nothing for an
    entire benchmark run before anyone noticed the missing rows."""
    span_fns = {"span", "phase"}
    findings = []
    for src in ctx.python_files:
        gp_aliases = _module_aliases(src.tree, _EVENTS_MODULE)
        tr_aliases = _module_aliases(src.tree, _SPANS_MODULE)
        aliases = gp_aliases | tr_aliases
        if not aliases:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in span_fns and \
                    isinstance(call.func.value, ast.Name) and \
                    call.func.value.id in aliases:
                findings.append(Finding(
                    rule="trace-span-no-with", path=src.rel,
                    line=node.lineno,
                    message=(f"{call.func.value.id}."
                             f"{call.func.attr}(...) called as a "
                             f"bare statement opens a span that "
                             f"never closes; use `with`")))
    return findings

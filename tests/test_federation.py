"""Federation meta-scheduler tests: constraint filtering, greedy
best-fit, end-to-end scheduling onto fake pools, HA lock, zap."""

import json
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.federation import federation as fed
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_pool(store, substrate, pool_id, accel="v5litepod-4"):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": accel},
        "max_wait_time_seconds": 30}}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return pool


@pytest.fixture()
def env():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    yield store, substrate
    substrate.stop_all()


def test_federation_crud(env):
    store, _ = env
    fed.create_federation(store, "f1")
    with pytest.raises(ValueError):
        fed.create_federation(store, "f1")
    fed.add_pool_to_federation(store, "f1", "pa")
    fed.add_pool_to_federation(store, "f1", "pb")
    assert fed.get_federation(store, "f1")["pools"] == ["pa", "pb"]
    fed.remove_pool_from_federation(store, "f1", "pa")
    assert fed.get_federation(store, "f1")["pools"] == ["pb"]
    fed.destroy_federation(store, "f1")
    with pytest.raises(ValueError):
        fed.get_federation(store, "f1")


def test_constraint_filter_and_best_fit(env):
    store, substrate = env
    make_pool(store, substrate, "small", "v5litepod-4")
    make_pool(store, substrate, "big", "v5litepod-16")
    facts = [f for f in (fed._pool_facts(store, p)
                         for p in ("small", "big")) if f]
    assert len(facts) == 2
    eligible = fed.filter_pools_hard_constraints(
        facts, {"min_chips": 8})
    assert [f["pool_id"] for f in eligible] == ["big"]
    # No constraints: best fit prefers most idle nodes (big pool).
    choice = fed.greedy_best_fit(
        fed.filter_pools_hard_constraints(facts, {}))
    assert choice["pool_id"] == "big"
    # Generation mismatch filters everything.
    assert fed.filter_pools_hard_constraints(
        facts, {"accelerator_generation": "v6e"}) == []


def test_end_to_end_federated_job(env):
    store, substrate = env
    make_pool(store, substrate, "cpuish", "v5litepod-4")
    make_pool(store, substrate, "podpool", "v5litepod-16")
    fed.create_federation(store, "fed1")
    fed.add_pool_to_federation(store, "fed1", "cpuish")
    fed.add_pool_to_federation(store, "fed1", "podpool")
    jobs_config = {"job_specifications": [{
        "id": "fj",
        "federation_constraints": {"min_chips": 16},
        "tasks": [{"command": "echo federated"}],
    }]}
    fed.submit_job_to_federation(store, "fed1", jobs_config)
    proc = fed.FederationProcessor(store)
    assert proc.process_once() == 1
    rows = fed.list_federation_jobs(store, "fed1")
    assert rows[0]["pool_id"] == "podpool"
    tasks = jobs_mgr.wait_for_tasks(store, "podpool", "fj", timeout=30)
    assert tasks[0]["state"] == "completed"


def test_unschedulable_job_requeues_then_schedules(env):
    store, substrate = env
    fed.create_federation(store, "fed2")
    jobs_config = {"job_specifications": [{
        "id": "fq", "tasks": [{"command": "echo late"}]}]}
    fed.submit_job_to_federation(store, "fed2", jobs_config)
    proc = fed.FederationProcessor(store, action_retry_delay=0.1)
    assert proc.process_once() == 0  # no pools yet -> backoff
    make_pool(store, substrate, "late-pool", "v5litepod-4")
    fed.add_pool_to_federation(store, "fed2", "late-pool")
    time.sleep(0.2)  # let the action become visible again
    assert proc.process_once() == 1
    jobs_mgr.wait_for_tasks(store, "late-pool", "fq", timeout=30)


def test_zap_drops_action(env):
    store, substrate = env
    fed.create_federation(store, "fed3")
    action_id = fed.submit_job_to_federation(
        store, "fed3", {"job_specifications": [{
            "id": "poison", "tasks": [{"command": "echo x"}]}]})
    fed.zap_action(store, "fed3", action_id)
    proc = fed.FederationProcessor(store)
    proc.process_once()
    from batch_shipyard_tpu.state import names
    assert store.queue_length(names.federation_queue("fed3")) == 0


def test_ha_single_scheduler(env):
    store, _ = env
    fed.create_federation(store, "fed4")
    proc_a = fed.FederationProcessor(store, owner="a")
    proc_b = fed.FederationProcessor(store, owner="b")
    assert proc_a._hold_global_lock()
    assert not proc_b._hold_global_lock()
    # a renews fine; b still locked out
    assert proc_a._hold_global_lock()
    assert not proc_b._hold_global_lock()


def test_federated_job_term_and_del_routing(env):
    store, substrate = env
    make_pool(store, substrate, "routed", "v5litepod-4")
    fed.create_federation(store, "fedr")
    fed.add_pool_to_federation(store, "fedr", "routed")
    fed.submit_job_to_federation(store, "fedr", {
        "job_specifications": [{
            "id": "rjob", "tasks": [{"command": "sleep 60"}]}]})
    fed.FederationProcessor(store).process_once()
    assert fed.locate_federation_job(store, "fedr",
                                     "rjob") == "routed"
    pool_id = fed.terminate_federation_job(store, "fedr", "rjob")
    assert pool_id == "routed"
    assert jobs_mgr.get_job(store, "routed", "rjob")[
        "state"] == "terminated"
    assert fed.delete_federation_job(store, "fedr",
                                     "rjob") == "routed"
    with pytest.raises(jobs_mgr.JobNotFoundError):
        jobs_mgr.get_job(store, "routed", "rjob")
    with pytest.raises(ValueError):
        fed.locate_federation_job(store, "fedr", "rjob")

"""Attention kernels: reference, blockwise (memory-efficient), and
Pallas flash-attention forward+backward kernels for the TPU MXU.

Layout convention throughout: q/k/v are [batch, seq, heads, head_dim]
(bfloat16 on TPU; accumulation in float32).

  - ``mha_reference``: O(T^2) materialized-scores attention, the
    correctness oracle.
  - ``blockwise_mha``: lax.scan over KV blocks with online softmax —
    O(T) memory, fully differentiable (the building block ring
    attention runs per step). This is the XLA-friendly formulation:
    static shapes, no data-dependent control flow.
  - ``flash_attention``: Pallas TPU kernels for forward AND backward.
    Forward: grid over batch*heads x q-blocks, KV streamed through
    VMEM, logsumexp rows saved. Backward: a single fused kernel (grid
    over kv-blocks, streaming Q) producing dk/dv per block while dq
    accumulates in a VMEM fp32 scratch across the sequential grid —
    P is reconstructed from the saved logsumexp exactly once per
    (q, kv) tile, which matters because the backward is exp/VPU-bound
    on v5e. ~6x faster than the autodiff-of-blockwise backward.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Default flash kernel tiles (tuned on v5e; see bench history). The
# dispatcher guard and ring_attention's tiling check both derive from
# these — change them in one place only.
FLASH_BLOCK_Q = 512
FLASH_BLOCK_K = 1024


def flash_shapes_ok(t_q: int, t_kv: int) -> bool:
    """Can the default flash blocks tile these sequence lengths?
    Blocks clamp to the sequence, so short sequences are fine only if
    they are themselves MXU-tileable (128-aligned)."""
    def ok(t, block):
        if t < block:
            return t % 128 == 0
        return t % block == 0
    return ok(t_q, FLASH_BLOCK_Q) and ok(t_kv, FLASH_BLOCK_K)


def _causal_mask(q_positions, k_positions):
    """[Tq, Tk] True where attention is allowed (k <= q)."""
    return q_positions[:, None] >= k_positions[None, :]


def mha_reference(q, k, v, causal: bool = True,
                  q_offset: int = 0, kv_offset: int = 0):
    """Plain attention; the numerics oracle for the fast paths."""
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(depth)
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[1], 1), 0)[:, 0]
        k_pos = kv_offset + jax.lax.broadcasted_iota(
            jnp.int32, (k.shape[1], 1), 0)[:, 0]
        mask = _causal_mask(q_pos, k_pos)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ----------------------- online-softmax accumulation -------------------

def attention_block_update(q, k_blk, v_blk, o, m, l, *, causal: bool,
                           q_offset, kv_offset, scale: float):
    """One online-softmax accumulation step against a KV block.

    q: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D]
    o: [B, Tq, H, D] float32 numerator
    m: [B, H, Tq] running max; l: [B, H, Tq] running denominator.
    q_offset/kv_offset: global positions (ints or traced scalars).
    """
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[1], 1), 0)[:, 0]
        k_pos = kv_offset + jax.lax.broadcasted_iota(
            jnp.int32, (k_blk.shape[1], 1), 0)[:, 0]
        mask = _causal_mask(q_pos, k_pos)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # exp with stable max; rows with no valid keys stay at -inf max and
    # contribute nothing (exp(-inf - -inf) handled via where).
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def attention_init(q):
    batch, t_q, heads, depth = q.shape
    o = jnp.zeros((batch, t_q, heads, depth), dtype=jnp.float32)
    m = jnp.full((batch, heads, t_q), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((batch, heads, t_q), dtype=jnp.float32)
    return o, m, l


def attention_finalize(q, o, m, l):
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def blockwise_mha(q, k, v, causal: bool = True, block_size: int = 512,
                  q_offset: int = 0, kv_offset: int = 0):
    """Memory-efficient attention: scan KV blocks with online softmax."""
    batch, t_kv = k.shape[0], k.shape[1]
    block_size = min(block_size, t_kv)
    if t_kv % block_size:
        raise ValueError(
            f"kv length {t_kv} not divisible by block {block_size}")
    num_blocks = t_kv // block_size
    scale = 1.0 / math.sqrt(q.shape[-1])
    k_blocks = k.reshape(batch, num_blocks, block_size, *k.shape[2:])
    v_blocks = v.reshape(batch, num_blocks, block_size, *v.shape[2:])

    # Rematerialize each block update: without this, the scan's
    # backward saves every block's score/probability matrices
    # ([B,H,Tq,block] fp32 per step — gigabytes per layer), defeating
    # the whole point of blockwise attention. With it, the backward
    # recomputes scores per block (the flash-attention property).
    @jax.checkpoint
    def step(carry, blk):
        o, m, l = carry
        k_blk, v_blk, blk_idx = blk
        o, m, l = attention_block_update(
            q, k_blk, v_blk, o, m, l, causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset + blk_idx * block_size, scale=scale)
        return (o, m, l), None

    carry = attention_init(q)
    (o, m, l), _ = jax.lax.scan(
        step, carry,
        (k_blocks.transpose(1, 0, 2, 3, 4),
         v_blocks.transpose(1, 0, 2, 3, 4),
         jnp.arange(num_blocks)))
    return attention_finalize(q, o, m, l)


# --------------------------- pallas forward ----------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, causal: bool, scale: float,
                      q_block: int):
    """One (batch*head, q-block) program: stream KV blocks via the
    grid-blocked refs and accumulate with online softmax in VMEM.
    Also emits the logsumexp rows consumed by the backward kernels."""
    qi = pl.program_id(1)
    # Operands stay in their input dtype (bf16 in production): the MXU
    # multiplies bf16 x bf16 with exact fp32 accumulation at full rate,
    # where pre-casting to fp32 forces the ~3x-slower multi-pass mode.
    q_tile = q_ref[...]  # [q_block, D]
    t_kv = k_ref.shape[0]
    num_kb = t_kv // block_k

    def make_body(masked: bool):
        def body(kb, carry):
            o, m, l = carry
            k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
            scores = jax.lax.dot_general(
                q_tile, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [qb, kb]
            if masked:
                q_pos = (qi * q_block + jax.lax.broadcasted_iota(
                    jnp.int32, (q_block, block_k), 0))
                k_pos = (kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (q_block, block_k), 1))
                scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
            m_blk = jnp.max(scores, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            correction = jnp.exp(m - m_new)
            p = jnp.exp(scores - m_new[:, None])
            l_new = l * correction + jnp.sum(p, axis=-1)
            pv = jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return o * correction[:, None] + pv, m_new, l_new

        return body

    o = jnp.zeros((q_block, q_ref.shape[-1]), dtype=jnp.float32)
    m = jnp.full((q_block,), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((q_block,), dtype=jnp.float32)
    if causal:
        # KV blocks fully below the diagonal need no mask; only blocks
        # straddling it do, and blocks past it contribute nothing
        # (exact ceil — the old floor+1 bound ran a fully-masked
        # wasted block whenever the division was exact).
        n_full = qi * q_block // block_k
        upper = jnp.minimum(
            num_kb, ((qi + 1) * q_block + block_k - 1) // block_k)
        o, m, l = jax.lax.fori_loop(0, n_full, make_body(False),
                                    (o, m, l))
        o, m, l = jax.lax.fori_loop(n_full, upper, make_body(True),
                                    (o, m, l))
    else:
        o, m, l = jax.lax.fori_loop(0, num_kb, make_body(False),
                                    (o, m, l))
    denom = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (o / denom[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(denom))[:, None]


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   with_lse: bool = False):
    batch, t_q, heads, depth = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(depth)
    # Collapse batch/heads into the grid's first dimension.
    q_r = q.transpose(0, 2, 1, 3).reshape(batch * heads, t_q, depth)
    k_r = k.transpose(0, 2, 1, 3).reshape(batch * heads, t_kv, depth)
    v_r = v.transpose(0, 2, 1, 3).reshape(batch * heads, t_kv, depth)
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    if t_q % block_q or t_kv % block_k:
        raise ValueError(
            f"flash attention requires seq lengths divisible by block "
            f"sizes: t_q={t_q} block_q={block_q}, t_kv={t_kv} "
            f"block_k={block_k}")
    grid = (batch * heads, t_q // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k,
                          causal=causal, scale=scale, q_block=block_q),
        out_shape=(
            jax.ShapeDtypeStruct((batch * heads, t_q, depth), q.dtype),
            # Trailing singleton keeps the block 2D for the TPU
            # tiling rules (lane dim == full array dim of 1).
            jax.ShapeDtypeStruct((batch * heads, t_q, 1),
                                 jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, depth),
                         lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t_kv, depth), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t_kv, depth), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, depth),
                         lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q, 1),
                         lambda bh, qi: (bh, qi, 0)),
        ),
    )(q_r, k_r, v_r)
    out = out.reshape(batch, heads, t_q, depth).transpose(0, 2, 1, 3)
    if with_lse:
        # lse stays [B*H, T, 1] (trailing singleton for TPU tiling)
        # for the backward kernels.
        return out, lse
    return out


def _flash_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_acc, *,
                      block_q: int, causal: bool, scale: float,
                      k_block: int):
    """Fused backward for one (batch*head, kv-block): stream Q blocks.

    dV = P^T @ dO; dK = scale * dS^T @ Q — and dQ accumulates into a
    VMEM fp32 scratch across the (sequential) kv-block grid dimension,
    so P = exp(S - lse) and the score matmul are computed ONCE per
    (q, kv) tile instead of once in a dq kernel and again in a dkv
    kernel. On a v5e chip the backward is exp/VPU-bound, so the fusion
    is worth ~1.5x on the whole backward.
    """
    kb = pl.program_id(1)
    num_kb = pl.num_programs(1)
    k_tile = k_ref[...]
    v_tile = v_ref[...]
    t_q = q_ref.shape[0]
    num_qb = t_q // block_q

    @pl.when(kb == 0)
    def _zero_dq():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def make_body(masked: bool):
        def body(qi, carry):
            dk, dv = carry
            q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
            do_blk = do_ref[pl.ds(qi * block_q, block_q), :]
            lse_blk = lse_ref[pl.ds(qi * block_q, block_q), 0]
            delta_blk = delta_ref[pl.ds(qi * block_q, block_q), 0]
            scores = jax.lax.dot_general(
                q_blk, k_tile, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [qb, kb]
            if masked:
                q_pos = (qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, k_block), 0))
                k_pos = (kb * k_block + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, k_block), 1))
                scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
            p = jnp.exp(scores - lse_blk[:, None])  # [qb, kb]
            dv = dv + jax.lax.dot_general(
                p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [kb, D]
            dp = jax.lax.dot_general(
                do_blk, v_tile, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [qb, kb]
            ds = p * (dp - delta_blk[:, None])
            dk = dk + jax.lax.dot_general(
                ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [kb, D]
            dq_blk = jax.lax.dot_general(
                ds.astype(k_tile.dtype), k_tile,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [qb, D]
            dq_acc[pl.ds(qi * block_q, block_q), :] = (
                dq_acc[pl.ds(qi * block_q, block_q), :] + dq_blk)
            return dk, dv

        return body

    if causal:
        # Q blocks strictly before the diagonal see nothing of this
        # KV block; blocks past the diagonal need no mask at all.
        lower = (kb * k_block) // block_q
        first_full = ((kb + 1) * k_block + block_q - 1) // block_q
    else:
        lower = 0
        first_full = 0
    zeros = (jnp.zeros((k_block, k_ref.shape[-1]), dtype=jnp.float32),
             jnp.zeros((k_block, v_ref.shape[-1]), dtype=jnp.float32))
    dk, dv = jax.lax.fori_loop(
        lower, jnp.minimum(first_full, num_qb),
        make_body(masked=causal), zeros)
    dk, dv = jax.lax.fori_loop(
        jnp.maximum(lower, jnp.minimum(first_full, num_qb)), num_qb,
        make_body(masked=False), (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)

    @pl.when(kb == num_kb - 1)
    def _emit_dq():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, block_q: int,
                    block_k: int, g_lse=None):
    batch, t_q, heads, depth = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(depth)
    # The fused kernel keeps more live [block_q, block_k] fp32
    # temporaries than the forward (p, dp, ds + casts), so its q-block
    # is halved — and the k-block too for fp32 inputs, whose resident
    # Q/dO/KV buffers are twice the size — to stay inside the ~16MB
    # VMEM scoped-stack budget.
    block_q = min(block_q, t_q, 256)
    block_k = min(block_k, t_kv)
    if jnp.dtype(q.dtype).itemsize >= 4:
        block_k = min(block_k, 512)
    bh = batch * heads
    q_r = q.transpose(0, 2, 1, 3).reshape(bh, t_q, depth)
    k_r = k.transpose(0, 2, 1, 3).reshape(bh, t_kv, depth)
    v_r = v.transpose(0, 2, 1, 3).reshape(bh, t_kv, depth)
    do_r = g.transpose(0, 2, 1, 3).reshape(bh, t_q, depth)
    o_r = out.transpose(0, 2, 1, 3).reshape(bh, t_q, depth)
    # delta = rowsum(dO * O), the softmax-normalizer correction term.
    delta = jnp.sum(do_r.astype(jnp.float32) * o_r.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if g_lse is not None:
        # Cotangent on the lse output enters the score gradient as
        # ds_j = p_j * (dP_j - delta + g_lse)  — because d lse/d s_j
        # = p_j — i.e. exactly a correction to delta. This is what
        # makes the ring merge (whose weights depend on each block's
        # lse) differentiate correctly through the per-block kernels.
        delta = delta - g_lse.astype(jnp.float32)
    q_full = pl.BlockSpec((None, t_q, depth), lambda b, i: (b, 0, 0))
    row_full = pl.BlockSpec((None, t_q, 1), lambda b, i: (b, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_kernel, block_q=block_q,
                          causal=causal, scale=scale, k_block=block_k),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t_q, depth), q.dtype),
            jax.ShapeDtypeStruct((bh, t_kv, depth), k.dtype),
            jax.ShapeDtypeStruct((bh, t_kv, depth), v.dtype),
        ),
        grid=(bh, t_kv // block_k),
        in_specs=[
            q_full,
            pl.BlockSpec((None, block_k, depth),
                         lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, depth),
                         lambda b, i: (b, i, 0)),
            q_full,
            row_full, row_full,
        ],
        out_specs=(
            q_full,
            pl.BlockSpec((None, block_k, depth),
                         lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, depth),
                         lambda b, i: (b, i, 0)),
        ),
        scratch_shapes=[pltpu.VMEM((t_q, depth), jnp.float32)],
    )(q_r, k_r, v_r, do_r, lse, delta)

    def unflatten(x, t_len):
        return x.reshape(batch, heads, t_len, depth).transpose(
            0, 2, 1, 3)

    return (unflatten(dq, t_q), unflatten(dk, t_kv),
            unflatten(dv, t_kv))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = FLASH_BLOCK_Q,
                    block_k: int = FLASH_BLOCK_K):
    """Pallas flash attention: hand kernels for forward AND backward
    (dq + dkv kernels over saved logsumexp rows)."""
    return _flash_forward(q, k, v, causal, block_q, block_k)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, out, lse, g, causal, block_q,
                           block_k)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal: bool = True,
                             block_q: int = FLASH_BLOCK_Q,
                             block_k: int = FLASH_BLOCK_K):
    """flash_attention variant that also returns the logsumexp rows
    ([B*H, T, 1] fp32) — the ring-attention building block (block
    results are merged across rotations in logsumexp space)."""
    return _flash_forward(q, k, v, causal, block_q, block_k,
                          with_lse=True)


def _flash_lse_fwd_rule(q, k, v, causal, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              with_lse=True)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd_rule(causal, block_q, block_k, residuals, grads):
    q, k, v, out, lse = residuals
    g, g_lse = grads
    return _flash_backward(q, k, v, out, lse, g, causal, block_q,
                           block_k, g_lse=g_lse)


flash_attention_with_lse.defvjp(_flash_lse_fwd_rule,
                                _flash_lse_bwd_rule)


def merge_attention_blocks(o1, lse1, o2, lse2):
    """Merge two normalized attention partials in logsumexp space.

    o_i: [B, T, H, D] (any float dtype); lse_i: [B*H, T, 1] fp32 with
    -inf marking fully-masked rows. Returns (o, lse) of the combined
    attention over the union of the two key sets.
    """
    batch, t_len, heads, depth = o1.shape
    l1 = lse1.reshape(batch, heads, t_len).transpose(0, 2, 1)
    l2 = lse2.reshape(batch, heads, t_len).transpose(0, 2, 1)
    m = jnp.maximum(l1, l2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(l1 > _NEG_INF / 2, jnp.exp(l1 - m_safe), 0.0)
    w2 = jnp.where(l2 > _NEG_INF / 2, jnp.exp(l2 - m_safe), 0.0)
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o1.astype(jnp.float32) * (w1 / denom_safe)[..., None] +
         o2.astype(jnp.float32) * (w2 / denom_safe)[..., None])
    lse = jnp.where(denom > 0.0, m_safe + jnp.log(denom_safe),
                    _NEG_INF)
    lse = lse.transpose(0, 2, 1).reshape(batch * heads, t_len, 1)
    return o.astype(o1.dtype), lse


def masked_attention_block(q):
    """The identity element for merge_attention_blocks: zero output,
    -inf logsumexp (no keys visible)."""
    batch, t_len, heads, _depth = q.shape
    return (jnp.zeros_like(q),
            jnp.full((batch * heads, t_len, 1), _NEG_INF, jnp.float32))


def attention(q, k, v, causal: bool = True,
              impl: Optional[str] = None, block_size: int = 512):
    """Dispatch: 'flash' (pallas fwd), 'blockwise', or 'reference'.
    Default: flash on TPU (falling back to blockwise for shapes the
    kernel can't tile), blockwise elsewhere."""
    if impl is None:
        impl = ("flash" if jax.default_backend() == "tpu"
                else "blockwise")
        if impl == "flash" and not flash_shapes_ok(q.shape[1],
                                                   k.shape[1]):
            impl = "blockwise"
            block_size = math.gcd(k.shape[1], block_size) or k.shape[1]
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "blockwise":
        return blockwise_mha(q, k, v, causal, block_size=block_size)
    if impl == "reference":
        return mha_reference(q, k, v, causal)
    raise ValueError(f"unknown attention impl {impl!r}")

"""Text-generation payload: KV-cache decode benchmark/demo.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.generate \
        --num-tokens 128 --batch 8 --temperature 0.8 --top-k 40
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.models import inference, transformer as tfm
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=12)
    parser.add_argument("--n-heads", type=int, default=16)
    parser.add_argument("--d-ff", type=int, default=2816)
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=32)
    parser.add_argument("--num-tokens", type=int, default=128)
    parser.add_argument("--max-decode-len", type=int, default=512)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    # Speculative decoding: a small draft model proposes, the target
    # validates blocks (greedy-exact; models/inference.py).
    parser.add_argument("--speculative", action="store_true")
    parser.add_argument("--draft-d-model", type=int, default=256)
    parser.add_argument("--draft-n-layers", type=int, default=2)
    parser.add_argument("--gamma", type=int, default=4,
                        help="Draft tokens proposed per round")
    args = parser.parse_args()

    ctx = distributed.setup()
    config = tfm.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=args.d_ff,
        max_seq_len=args.max_decode_len, dtype=jnp.bfloat16)
    model = tfm.TransformerLM(config)
    rng = np.random.RandomState(args.seed)
    params = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, args.prompt_len), jnp.int32))["params"]
    prompt = jnp.asarray(
        rng.randint(0, args.vocab, (args.batch, args.prompt_len)),
        jnp.int32)
    if args.speculative:
        if args.temperature > 0:
            raise SystemExit("--speculative is greedy-exact; drop "
                             "--temperature")
        draft_config = tfm.TransformerConfig(
            vocab_size=args.vocab, d_model=args.draft_d_model,
            n_layers=args.draft_n_layers, n_heads=args.n_heads,
            d_head=args.draft_d_model // args.n_heads,
            d_ff=args.draft_d_model * 3,
            max_seq_len=args.max_decode_len, dtype=jnp.bfloat16)
        draft_params = tfm.TransformerLM(draft_config).init(
            jax.random.PRNGKey(args.seed + 7),
            jnp.zeros((1, args.prompt_len), jnp.int32))["params"]
        run_spec, _, _ = inference.make_speculative_decoder(
            config, params, draft_config, draft_params,
            args.max_decode_len, gamma=args.gamma)
        out, stats = run_spec(prompt, args.num_tokens)
        int(out[0, -1])  # hard sync (compile + first run)
        start = time.perf_counter()
        out, stats = run_spec(prompt, args.num_tokens)
        int(out[0, -1])
        elapsed = time.perf_counter() - start
        tokens_per_sec = args.batch * args.num_tokens / elapsed
        acc = int(stats["accepted"]) / max(1, int(stats["proposed"]))
        distributed.log(ctx, (
            f"speculative generate: {tokens_per_sec:.1f} tok/s "
            f"(batch {args.batch}, {args.num_tokens} new tokens, "
            f"{int(stats['rounds'])} rounds, gamma={args.gamma}, "
            f"acceptance {acc:.2f})"))
        return 0
    run, _ = inference.make_decoder(config, params,
                                    args.max_decode_len)
    sampling = inference.SamplingConfig(
        temperature=args.temperature, top_k=args.top_k)
    key = jax.random.PRNGKey(args.seed)
    out, _cache = run(prompt, args.num_tokens, key, sampling=sampling)
    int(out[0, -1])  # hard sync (compile + first run)
    start = time.perf_counter()
    out, _cache = run(prompt, args.num_tokens,
                      jax.random.PRNGKey(args.seed + 1),
                      sampling=sampling)
    int(out[0, -1])
    elapsed = time.perf_counter() - start
    tokens_per_sec = args.batch * args.num_tokens / elapsed
    distributed.log(ctx, (
        f"generate: {tokens_per_sec:.1f} tok/s decode "
        f"(batch {args.batch}, {args.num_tokens} new tokens, "
        f"{elapsed / args.num_tokens * 1000:.1f} ms/token-step)"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""`shipyard lint` analyzer tests: every rule family fires on its bad
shape and stays silent on the blessed shape, suppression and baseline
semantics hold, and — the tier-1 gate — the repo itself is lint-clean
against the checked-in baseline.

Fixtures are inline source snippets fed through
AnalysisContext.from_strings, so each test pins exactly one shape; no
JAX, no store, milliseconds each.
"""

from collections import Counter

import pytest

from batch_shipyard_tpu import analysis
from batch_shipyard_tpu.analysis import core, rules_registry


def _run(sources: dict, rule_id: str):
    ctx = analysis.AnalysisContext.from_strings(sources)
    active, suppressed = analysis.run_rules(ctx, [rule_id])
    return active, suppressed


def _rules_of(sources: dict, rule_id: str):
    active, _ = _run(sources, rule_id)
    return active


# ------------------------------ framework ------------------------------

def test_every_rule_has_family_and_provenance():
    assert len(analysis.RULES) >= 20
    families = {r.family for r in analysis.RULES.values()}
    # The five tentpole families plus wiring, shell, and sim.
    assert {"store", "loop", "env", "registry", "jax", "wiring",
            "shell", "sim"} <= families
    for r in analysis.RULES.values():
        assert r.doc.strip(), r.id
        assert "Provenance" in r.doc, (
            f"rule {r.id} docstring must name the real bug it "
            f"descends from")


def test_unknown_rule_id_raises():
    ctx = analysis.AnalysisContext.from_strings({})
    with pytest.raises(KeyError):
        analysis.run_rules(ctx, ["no-such-rule"])


_FIRING_STORE = {
    "batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bad(store):\n"
        "    store.upsert_entity(names.TABLE_TASKS, 'pk', 'rk',\n"
        "                        {'x': 1})\n"
    )}


def test_inline_suppression_on_offending_line():
    src = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bad(store):\n"
        "    store.upsert_entity(names.TABLE_TASKS, 'pk', 'rk', "
        "{'x': 1})  # shipyard-lint: disable=store-blind-upsert\n")}
    active, suppressed = _run(src, "store-blind-upsert")
    assert not active and len(suppressed) == 1


def test_trailing_suppression_does_not_bleed_to_next_line():
    """A trailing directive covers ITS line only — an unrelated
    violation directly below a justified one must still fail."""
    src = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bad(store):\n"
        "    store.upsert_entity(names.TABLE_TASKS, 'pk', 'rk', "
        "{'x': 1})  # shipyard-lint: disable=store-blind-upsert\n"
        "    store.upsert_entity(names.TABLE_GANGS, 'pk', 'rk', "
        "{'x': 1})\n")}
    active, suppressed = _run(src, "store-blind-upsert")
    assert len(active) == 1 and len(suppressed) == 1
    assert "gangs" in active[0].message


def test_suppression_on_line_above():
    src = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bad(store):\n"
        "    # shipyard-lint: disable=store-blind-upsert\n"
        "    store.upsert_entity(names.TABLE_TASKS, 'pk', 'rk', "
        "{'x': 1})\n")}
    active, suppressed = _run(src, "store-blind-upsert")
    assert not active and len(suppressed) == 1


def test_file_level_suppression_in_prologue_only():
    fire = "X=`date`\n" * 20
    head = "#!/bin/sh\n# shipyard-lint: disable-file=" \
           "shell-backtick-subst\n"
    active, suppressed = _run({"tools/a.sh": head + fire},
                              "shell-backtick-subst")
    assert not active and len(suppressed) == 20
    # Past the 10-line prologue the directive is inert.
    late = "#!/bin/sh\n" + "true\n" * 12 + \
        "# shipyard-lint: disable-file=shell-backtick-subst\n" + \
        "X=`date`\n"
    active, _ = _run({"tools/b.sh": late}, "shell-backtick-subst")
    assert len(active) == 1


def test_baseline_split_and_stale_detection(tmp_path):
    ctx = analysis.AnalysisContext.from_strings(_FIRING_STORE)
    active, _ = analysis.run_rules(ctx, ["store-blind-upsert"])
    assert len(active) == 1
    # Baselined: the finding warns instead of failing.
    baseline = Counter({active[0].fingerprint(): 1})
    report = analysis.analyze(ctx=ctx,
                              rule_ids=["store-blind-upsert"],
                              baseline=baseline)
    assert not report.new and len(report.baselined) == 1
    assert not report.stale_baseline
    # Stale: a baseline entry whose finding was fixed is reported so
    # triage debt shrinks monotonically.
    fixed_ctx = analysis.AnalysisContext.from_strings(
        {"batch_shipyard_tpu/mod.py": "x = 1\n"})
    report = analysis.analyze(ctx=fixed_ctx,
                              rule_ids=["store-blind-upsert"],
                              baseline=baseline)
    assert not report.new and not report.baselined
    assert report.stale_baseline == [active[0].fingerprint()]


def test_partial_rule_run_scopes_baseline():
    """`--rules X` judges only rule X's slice of the baseline: other
    rules' triaged entries are out of scope, not stale — a scoped run
    on a healthy tree must stay clean."""
    ctx = analysis.AnalysisContext.from_strings(_FIRING_STORE)
    other = Counter({("shell-backtick-subst", "tools/x.sh",
                      "backtick command substitution; use $(...)"): 1})
    active, _ = analysis.run_rules(ctx, ["store-blind-upsert"])
    baseline = other + Counter({active[0].fingerprint(): 1})
    report = analysis.analyze(ctx=ctx,
                              rule_ids=["store-blind-upsert"],
                              baseline=baseline)
    assert not report.new and not report.stale_baseline
    assert len(report.baselined) == 1


def test_baseline_write_is_deterministic(tmp_path):
    # Two findings, so the write exercises real ordering (a
    # single-element list would hide sort bugs).
    src = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bad(store):\n"
        "    store.upsert_entity(names.TABLE_TASKS, 'pk', 'rk', "
        "{'x': 1})\n"
        "    store.upsert_entity(names.TABLE_GANGS, 'pk', 'rk', "
        "{'x': 1})\n")}
    ctx = analysis.AnalysisContext.from_strings(src)
    active, _ = analysis.run_rules(ctx, ["store-blind-upsert"])
    assert len(active) == 2
    p1, p2 = tmp_path / "b1.json", tmp_path / "b2.json"
    analysis.write_baseline(p1, list(reversed(active)))
    analysis.write_baseline(p2, active)
    assert p1.read_bytes() == p2.read_bytes()
    loaded = analysis.load_baseline(p1)
    assert loaded == Counter(f.fingerprint() for f in active)


# ---------------------------- store family -----------------------------

def test_store_blind_upsert_fires_and_blessed_shapes_pass():
    assert len(_rules_of(_FIRING_STORE, "store-blind-upsert")) == 1
    # Local-constant indirection resolves too (the schedules.py
    # shape that motivated the rule).
    via_const = {"batch_shipyard_tpu/mod.py": (
        "_T = 'gangs'\n"
        "def bad(store):\n"
        "    store.upsert_entity(_T, 'pk', 'rk', {'x': 1})\n")}
    assert len(_rules_of(via_const, "store-blind-upsert")) == 1
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def good(store, row):\n"
        "    store.upsert_entity(names.TABLE_MONITOR, 'pk', 'rk',\n"
        "                        {'x': 1})\n"
        "    store.merge_entity(names.TABLE_TASKS, 'pk', 'rk',\n"
        "                       {'x': 1}, if_match=row['_etag'])\n"
        "    store.insert_entity(names.TABLE_TASKS, 'pk', 'rk',\n"
        "                        {'x': 1})\n")}
    assert not _rules_of(blessed, "store-blind-upsert")


def test_store_rmw_no_etag_fires_on_derived_write():
    firing = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bump(store):\n"
        "    row = store.get_entity(names.TABLE_TASKS, 'p', 'r')\n"
        "    count = int(row.get('n', 0))\n"
        "    store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                       {'n': count + 1})\n")}
    found = _rules_of(firing, "store-rmw-no-etag")
    assert len(found) == 1 and found[0].line == 5
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def bump(store):\n"
        "    row = store.get_entity(names.TABLE_TASKS, 'p', 'r')\n"
        "    count = int(row.get('n', 0))\n"
        "    store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                       {'n': count + 1},\n"
        "                       if_match=row['_etag'])\n")}
    assert not _rules_of(blessed, "store-rmw-no-etag")
    # A fresh-column stamp derives nothing from the read: allowed.
    stamp = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "def stamp(store):\n"
        "    row = store.get_entity(names.TABLE_TASKS, 'p', 'r')\n"
        "    if row.get('state') != 'running':\n"
        "        return\n"
        "    store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                       {'note': 'seen'})\n")}
    assert not _rules_of(stamp, "store-rmw-no-etag")


def test_store_etag_retry_requires_refetch():
    firing = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state.base import "
        "EtagMismatchError\n"
        "from batch_shipyard_tpu.state import names\n"
        "def retry(store, etag):\n"
        "    try:\n"
        "        store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                           {'x': 1}, if_match=etag)\n"
        "    except EtagMismatchError:\n"
        "        store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                           {'x': 1})\n")}
    assert len(_rules_of(firing, "store-etag-retry-no-refetch")) == 1
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state.base import "
        "EtagMismatchError\n"
        "from batch_shipyard_tpu.state import names\n"
        "def retry(store, etag):\n"
        "    try:\n"
        "        store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                           {'x': 1}, if_match=etag)\n"
        "    except EtagMismatchError:\n"
        "        row = store.get_entity(names.TABLE_TASKS, 'p',\n"
        "                               'r')\n"
        "        store.merge_entity(names.TABLE_TASKS, 'p', 'r',\n"
        "                           {'x': 1},\n"
        "                           if_match=row['_etag'])\n")}
    assert not _rules_of(blessed, "store-etag-retry-no-refetch")


# ----------------------------- loop family -----------------------------

def test_loop_unpartitioned_scan_needs_leader_gate():
    firing = {"batch_shipyard_tpu/agent/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        for row in self.store.query_entities(\n"
        "                names.TABLE_TASKS):\n"
        "            pass\n")}
    assert len(_rules_of(firing, "loop-unpartitioned-scan")) == 1
    gated = {"batch_shipyard_tpu/agent/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        if not self._is_gang_sweep_leader():\n"
        "            return\n"
        "        for row in self.store.query_entities(\n"
        "                names.TABLE_TASKS):\n"
        "            pass\n")}
    assert not _rules_of(gated, "loop-unpartitioned-scan")
    partitioned = {"batch_shipyard_tpu/agent/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        for row in self.store.query_entities(\n"
        "                names.TABLE_TASKS,\n"
        "                partition_key=self.pool_id):\n"
        "            pass\n")}
    assert not _rules_of(partitioned, "loop-unpartitioned-scan")


def test_leader_sweep_no_lease_requires_epoch_idiom():
    # A heartbeat-freshness election gates the scan rule but is NOT a
    # lease: the new rule still fires.
    elected = {"batch_shipyard_tpu/agent/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        if not self._is_gang_sweep_leader():\n"
        "            return\n"
        "        for row in self.store.query_entities(\n"
        "                names.TABLE_TASKS):\n"
        "            pass\n")}
    assert len(_rules_of(elected, "leader-sweep-no-lease")) == 1
    # The lease idiom (a leader_epoch call) is blessed.
    leased = {"batch_shipyard_tpu/agent/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        epoch = self._sweep_leader_epoch('janitor')\n"
        "        if epoch is None:\n"
        "            return\n"
        "        for row in self.store.query_entities(\n"
        "                names.TABLE_TASKS):\n"
        "            pass\n")}
    assert not _rules_of(leased, "leader-sweep-no-lease")
    # A leased sweep whose stamp does NOT thread the epoch through
    # still fires — the fencing is the point.
    unfenced = {"batch_shipyard_tpu/agent/mod.py": (
        "class A:\n"
        "    def _sweep_preempt(self):\n"
        "        epoch = self._sweep_leader_epoch('preempt')\n"
        "        if epoch is None:\n"
        "            return\n"
        "        request_preemption(self.store, 'p', 'j', 't')\n")}
    assert len(_rules_of(unfenced, "leader-sweep-no-lease")) == 1
    fenced = {"batch_shipyard_tpu/agent/mod.py": (
        "class A:\n"
        "    def _sweep_preempt(self):\n"
        "        epoch = self._sweep_leader_epoch('preempt')\n"
        "        if epoch is None:\n"
        "            return\n"
        "        request_preemption(self.store, 'p', 'j', 't',\n"
        "                           leader_epoch=epoch)\n")}
    assert not _rules_of(fenced, "leader-sweep-no-lease")
    # Non-sweep functions are out of scope (manual CLI preempts
    # carry their own follow-through).
    manual = {"batch_shipyard_tpu/agent/mod.py": (
        "def action_jobs_preempt(store):\n"
        "    request_preemption(store, 'p', 'j', 't')\n")}
    assert not _rules_of(manual, "leader-sweep-no-lease")


def test_loop_sleep_in_sweep_fires_only_on_hot_functions():
    firing = {"batch_shipyard_tpu/agent/mod.py": (
        "import time\n"
        "class A:\n"
        "    def _sweep_things(self):\n"
        "        time.sleep(1.0)\n")}
    assert len(_rules_of(firing, "loop-sleep-in-sweep")) == 1
    # Poll loops legitimately pace on sleep between empty polls.
    poll = {"batch_shipyard_tpu/agent/mod.py": (
        "import time\n"
        "class A:\n"
        "    def _worker_loop(self):\n"
        "        time.sleep(0.5)\n")}
    assert not _rules_of(poll, "loop-sleep-in-sweep")


# ------------------------------ env family -----------------------------

def test_env_read_unexported_fires_and_knobs_pass():
    firing = {"batch_shipyard_tpu/mod.py": (
        "import os\n"
        "V = os.environ.get('SHIPYARD_NOT_EXPORTED')\n")}
    assert len(_rules_of(firing, "env-read-unexported")) == 1
    exported = {"batch_shipyard_tpu/mod.py": (
        "import os\n"
        "V = os.environ.get('SHIPYARD_OK')\n"),
        "batch_shipyard_tpu/agent/mod.py": (
        "def launch(env):\n"
        "    env['SHIPYARD_OK'] = '1'\n")}
    assert not _rules_of(exported, "env-read-unexported")
    knob = {"batch_shipyard_tpu/mod.py": (
        "import os\n"
        "V = os.environ.get('SHIPYARD_RING_IMPL')\n")}
    assert not _rules_of(knob, "env-read-unexported")


def test_env_export_unread_honors_documented_contract():
    firing = {"batch_shipyard_tpu/agent/mod.py": (
        "def launch(env):\n"
        "    env['SHIPYARD_ORPHAN'] = '1'\n")}
    assert len(_rules_of(firing, "env-export-unread")) == 1
    documented = {"batch_shipyard_tpu/agent/task_runner.py": (
        '"""Env contract:\n\n'
        '  SHIPYARD_DOCUMENTED  exposed to user task commands\n'
        '"""\n'
        "def launch(env):\n"
        "    env['SHIPYARD_DOCUMENTED'] = '1'\n")}
    assert not _rules_of(documented, "env-export-unread")


def test_env_docker_unmapped_fires_on_dropped_contract_var():
    firing = {"batch_shipyard_tpu/agent/task_runner.py": (
        "def build_task_env(execution):\n"
        "    env = {}\n"
        "    env.update({\n"
        "        'SHIPYARD_POOL_ID': execution.pool_id,\n"
        "        'SHIPYARD_LOST': 'x',\n"
        "    })\n"
        "    return env\n"
        "def synthesize_command(execution):\n"
        "    argv = ['docker', 'run']\n"
        "    for var in ('SHIPYARD_POOL_ID',):\n"
        "        argv += ['-e', var]\n"
        "    return argv\n")}
    found = _rules_of(firing, "env-docker-unmapped")
    assert len(found) == 1 and "SHIPYARD_LOST" in found[0].message
    fixed = dict(firing)
    fixed["batch_shipyard_tpu/agent/task_runner.py"] = fixed[
        "batch_shipyard_tpu/agent/task_runner.py"].replace(
        "('SHIPYARD_POOL_ID',)", "('SHIPYARD_POOL_ID', "
        "'SHIPYARD_LOST')")
    assert not _rules_of(fixed, "env-docker-unmapped")
    # A variable named only in a COMMENT is not forwarded — the rule
    # must keep firing (deleting the -e line while keeping its
    # comment must not go green).
    commented = dict(firing)
    commented["batch_shipyard_tpu/agent/task_runner.py"] = commented[
        "batch_shipyard_tpu/agent/task_runner.py"].replace(
        "    argv = ['docker', 'run']\n",
        "    argv = ['docker', 'run']\n"
        "    # SHIPYARD_LOST is remapped below\n")
    found = _rules_of(commented, "env-docker-unmapped")
    assert len(found) == 1 and "SHIPYARD_LOST" in found[0].message
    # Nor in the DOCSTRING — prose must not count as forwarding.
    documented = dict(firing)
    documented["batch_shipyard_tpu/agent/task_runner.py"] = \
        documented["batch_shipyard_tpu/agent/task_runner.py"].replace(
        "def synthesize_command(execution):\n",
        "def synthesize_command(execution):\n"
        '    """SHIPYARD_LOST is forwarded below."""\n')
    found = _rules_of(documented, "env-docker-unmapped")
    assert len(found) == 1 and "SHIPYARD_LOST" in found[0].message


def test_env_docker_contract_holds_in_real_runner():
    """Regression anchor for the finding this rule caught in this
    PR: the real task_runner forwards every build_task_env var."""
    ctx = analysis.AnalysisContext.from_tree()
    active, _ = analysis.run_rules(ctx, ["env-docker-unmapped"])
    assert not active, [f.render() for f in active]


# --------------------------- registry family ---------------------------

def test_registry_table_undeclared_fires():
    firing = {"batch_shipyard_tpu/mod.py": (
        "def f(store):\n"
        "    store.get_entity('nosuchtable', 'p', 'r')\n")}
    assert len(_rules_of(firing, "registry-table-undeclared")) == 1
    attr = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "T = names.TABLE_BOGUS\n")}
    assert len(_rules_of(attr, "registry-table-undeclared")) == 1
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.state import names\n"
        "_T = 'tasks'\n"
        "def f(store):\n"
        "    store.get_entity(names.TABLE_TASKS, 'p', 'r')\n"
        "    store.get_entity(_T, 'p', 'r')\n")}
    assert not _rules_of(blessed, "registry-table-undeclared")


def test_registry_state_literal_fires():
    firing = {"batch_shipyard_tpu/mod.py": (
        "def f(row):\n"
        "    if row.get('state') == 'zombie':\n"
        "        return {'state': 'zombie'}\n")}
    assert len(_rules_of(firing, "registry-state-literal")) == 2
    blessed = {"batch_shipyard_tpu/mod.py": (
        "def f(row):\n"
        "    if row.get('state') in ('pending', 'RUNNING'):\n"
        "        return {'state': 'completed'}\n")}
    assert not _rules_of(blessed, "registry-state-literal")


def test_goodput_kind_undeclared_fires_via_alias():
    firing = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.goodput import events as gp\n"
        "def f(store):\n"
        "    gp.emit(store, 'p', gp.TASK_NOPE)\n")}
    found = _rules_of(firing, "goodput-kind-undeclared")
    assert len(found) == 1 and "TASK_NOPE" in found[0].message
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.goodput import events as gp\n"
        "def f(store):\n"
        "    gp.emit(store, 'p', gp.TASK_QUEUED)\n"
        "    path = gp.GOODPUT_FILE_ENV\n")}
    assert not _rules_of(blessed, "goodput-kind-undeclared")


def test_goodput_kind_unpriced_fires_when_marker_unregistered(
        monkeypatch):
    events_stub = {"batch_shipyard_tpu/goodput/events.py": (
        "EVENT_KINDS = frozenset()\n")}
    # Every real kind is priced or a declared marker.
    assert not _rules_of(events_stub, "goodput-kind-unpriced")
    # Un-declare the markers: the rule must catch the now-unpriced
    # interval kinds (this is what happens when someone registers a
    # new kind without teaching accounting about it).
    monkeypatch.setattr(rules_registry, "MARKER_EVENT_KINDS",
                        frozenset())
    found = _rules_of(events_stub, "goodput-kind-unpriced")
    # retry, preempt notice/exit, evicted, gang resize
    assert len(found) == 5


def test_trace_span_undeclared_fires_via_alias():
    firing = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.trace import spans as tr\n"
        "K = tr.SPAN_NOPE\n")}
    assert len(_rules_of(firing, "trace-span-undeclared")) == 1
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.trace import spans as tr\n"
        "K = tr.SPAN_SUBMIT\n")}
    assert not _rules_of(blessed, "trace-span-undeclared")


def test_trace_span_no_with_fires_on_bare_call():
    firing = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.goodput import events as gp\n"
        "def f():\n"
        "    gp.phase('compile')\n")}
    assert len(_rules_of(firing, "trace-span-no-with")) == 1
    blessed = {"batch_shipyard_tpu/mod.py": (
        "from batch_shipyard_tpu.goodput import events as gp\n"
        "def f():\n"
        "    with gp.phase('compile'):\n"
        "        pass\n")}
    assert not _rules_of(blessed, "trace-span-no-with")


# ----------------------------- jax family ------------------------------

def test_jax_impure_pure_fn_fires_in_contract_scope():
    firing = {"batch_shipyard_tpu/chaos/plan.py": (
        "import time\n"
        "class ChaosPlan:\n"
        "    def generate(cls, seed):\n"
        "        return time.time()\n")}
    assert len(_rules_of(firing, "jax-impure-pure-fn")) == 1
    # Seeded RNG is the mechanism, not a violation; and the same
    # call OUTSIDE a contract function is fine.
    blessed = {"batch_shipyard_tpu/chaos/plan.py": (
        "import random, time\n"
        "class ChaosPlan:\n"
        "    def generate(cls, seed):\n"
        "        rng = random.Random(seed)\n"
        "        return rng.uniform(0, 1)\n"
        "def run_drill():\n"
        "    return time.time()\n")}
    assert not _rules_of(blessed, "jax-impure-pure-fn")


def test_jax_donated_reuse_fires_on_stale_read():
    firing = {"batch_shipyard_tpu/mod.py": (
        "import jax\n"
        "step = jax.jit(lambda p, b: p, donate_argnums=(0,))\n"
        "def loop(params, batch):\n"
        "    loss = step(params, batch)\n"
        "    norm = params['w']\n"
        "    return loss, norm\n")}
    found = _rules_of(firing, "jax-donated-reuse")
    assert len(found) == 1 and found[0].line == 5
    # The blessed rebind-in-one-statement shape (multi-line call
    # included — the real train.py step_wrapper layout).
    blessed = {"batch_shipyard_tpu/mod.py": (
        "import functools, jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0, 1))\n"
        "def step(params, opt, batch):\n"
        "    return params, opt\n"
        "def loop(params, opt, batch):\n"
        "    params, opt = step(\n"
        "        params, opt, batch)\n"
        "    return params, opt\n")}
    assert not _rules_of(blessed, "jax-donated-reuse")


def test_jax_restore_no_drain_fires_without_wait():
    firing = {"batch_shipyard_tpu/workloads/mod.py": (
        "from batch_shipyard_tpu.workloads.checkpoint import (\n"
        "    AsyncCheckpointManager, restore)\n"
        "def resume(manager, tmpl):\n"
        "    return restore('dir', tmpl)\n")}
    assert len(_rules_of(firing, "jax-restore-no-drain")) == 1
    drained = {"batch_shipyard_tpu/workloads/mod.py": (
        "from batch_shipyard_tpu.workloads.checkpoint import (\n"
        "    AsyncCheckpointManager, restore)\n"
        "def resume(manager, tmpl):\n"
        "    manager.wait_until_finished()\n"
        "    return restore('dir', tmpl)\n")}
    assert not _rules_of(drained, "jax-restore-no-drain")
    guarded = {"batch_shipyard_tpu/workloads/mod.py": (
        "from batch_shipyard_tpu.workloads.checkpoint import (\n"
        "    AsyncCheckpointManager, restore)\n"
        "def resume(manager, tmpl):\n"
        "    if manager is not None:\n"
        "        return manager.restore(tmpl)\n"
        "    else:\n"
        "        return restore('dir', tmpl)\n")}
    assert not _rules_of(guarded, "jax-restore-no-drain")


def test_jax_blocking_save_in_train_fires():
    firing = {"batch_shipyard_tpu/workloads/train_foo.py": (
        "from batch_shipyard_tpu.workloads import checkpoint\n"
        "def main(params, opt):\n"
        "    checkpoint.save('dir', 1, params, opt)\n")}
    assert len(_rules_of(firing, "jax-blocking-save-in-train")) == 1
    blessed = {"batch_shipyard_tpu/workloads/train_foo.py": (
        "from batch_shipyard_tpu.workloads import checkpoint\n"
        "def main(ckpt, params, opt):\n"
        "    ckpt.step_save(1, params, opt)\n")}
    assert not _rules_of(blessed, "jax-blocking-save-in-train")


# ---------------------------- wiring family ----------------------------

def test_preempt_grace_unbounded_fires_and_blessed():
    """A sweep-cadence function stamping preemption notices with no
    escalate/evict call in reach = an unbounded grace window (the
    PR 12 bug class); the blessed shape calls an escalation helper.
    Non-sweep callers (manual CLI preempt, chaos injectors) are out
    of scope."""
    firing = {"batch_shipyard_tpu/mod.py": (
        "def _sweep_preemptions(self):\n"
        "    for row in rows:\n"
        "        request_preemption(store, 'p', 'j', 't')\n")}
    found = _rules_of(firing, "preempt-grace-unbounded")
    assert len(found) == 1
    assert "escalation" in found[0].message
    blessed = {"batch_shipyard_tpu/mod.py": (
        "def _sweep_preemptions(self):\n"
        "    for row in rows:\n"
        "        if overdue(row):\n"
        "            self._maybe_escalate_eviction(row)\n"
        "            continue\n"
        "        request_preemption(store, 'p', 'j', 't')\n")}
    assert not _rules_of(blessed, "preempt-grace-unbounded")
    # A non-sweep function stamping a notice (the manual override,
    # the chaos injector) is out of the rule's scope.
    manual = {"batch_shipyard_tpu/mod.py": (
        "def action_jobs_preempt(ctx):\n"
        "    request_preemption(ctx.store, 'p', 'j', 't')\n")}
    assert not _rules_of(manual, "preempt-grace-unbounded")


def test_wiring_cli_action_unwired_fires():
    firing = {
        "batch_shipyard_tpu/fleet.py": (
            "def action_orphan(ctx):\n"
            "    pass\n"),
        "batch_shipyard_tpu/cli/main.py": "x = 1\n"}
    found = _rules_of(firing, "wiring-cli-action-unwired")
    assert len(found) == 1 and "action_orphan" in found[0].message
    wired = {
        "batch_shipyard_tpu/fleet.py": (
            "def action_orphan(ctx):\n"
            "    pass\n"),
        "batch_shipyard_tpu/cli/main.py": (
            "from batch_shipyard_tpu import fleet\n"
            "def cmd():\n"
            "    fleet.action_orphan(None)\n")}
    assert not _rules_of(wired, "wiring-cli-action-unwired")


def test_wiring_kinds_help_stale_fires_on_hardcoded_help():
    firing = {"batch_shipyard_tpu/cli/main.py": (
        "import click\n"
        "@click.option('--kinds', help='store_delay,task_kill')\n"
        "def cmd(kinds):\n"
        "    pass\n")}
    assert len(_rules_of(firing, "wiring-kinds-help-stale")) == 1
    derived = {"batch_shipyard_tpu/cli/main.py": (
        "import click\n"
        "from batch_shipyard_tpu.chaos import plan as p\n"
        "@click.option('--kinds',\n"
        "              help=','.join(p.INJECTION_KINDS))\n"
        "def cmd(kinds):\n"
        "    pass\n"
        "@click.option('--kinds',\n"
        "              help=','.join(p.INJECTION_KINDS))\n"
        "def cmd2(kinds):\n"
        "    pass\n")}
    assert not _rules_of(derived, "wiring-kinds-help-stale")
    # A THIRD --kinds option with hand-written help must not hide
    # behind the two derived ones: one join per option.
    mixed = dict(derived)
    mixed["batch_shipyard_tpu/cli/main.py"] += (
        "@click.option('--kinds', help='store_delay,task_kill')\n"
        "def cmd3(kinds):\n"
        "    pass\n")
    assert len(_rules_of(mixed, "wiring-kinds-help-stale")) == 1


def test_wiring_compile_cache_optout_fires():
    firing = {"batch_shipyard_tpu/workloads/train_foo.py": (
        "from batch_shipyard_tpu.parallel import train\n"
        "def main():\n"
        "    train.TrainHarness\n")}
    assert len(_rules_of(firing, "wiring-compile-cache-optout")) == 2
    blessed = {"batch_shipyard_tpu/workloads/train_foo.py": (
        "from batch_shipyard_tpu.parallel import train\n"
        "from batch_shipyard_tpu import compilecache\n"
        "def main(args, parser):\n"
        "    compilecache.add_compile_cache_args(parser)\n"
        "    compilecache.enable_from_args(args)\n")}
    assert not _rules_of(blessed, "wiring-compile-cache-optout")


# ------------------------------ sim family -----------------------------

def test_sim_wall_clock_fires_on_time_reads_in_sim():
    """Wall-clock reads inside sim/ break the determinism contract
    (same seed+trace+policy => byte-identical report); every banned
    source form must fire."""
    firing = {"batch_shipyard_tpu/sim/simulator.py": (
        "import time\n"
        "def run():\n"
        "    return time.time()\n")}
    assert len(_rules_of(firing, "sim-wall-clock")) == 1
    mono = {"batch_shipyard_tpu/sim/scenarios.py": (
        "import time\n"
        "def build():\n"
        "    return time.monotonic()\n")}
    assert len(_rules_of(mono, "sim-wall-clock")) == 1
    dt = {"batch_shipyard_tpu/sim/scenarios.py": (
        "import datetime\n"
        "def build():\n"
        "    return datetime.datetime.now()\n")}
    assert len(_rules_of(dt, "sim-wall-clock")) == 1


def test_sim_wall_clock_blessed_shapes_pass():
    """clock.py is the ONE module allowed near wall-clock sources;
    non-sim files are out of scope (the live agent is built on
    time.time()); suppression works like every other rule."""
    clock = {"batch_shipyard_tpu/sim/clock.py": (
        "import time\n"
        "def _debug_now():\n"
        "    return time.time()\n")}
    assert not _rules_of(clock, "sim-wall-clock")
    live = {"batch_shipyard_tpu/agent/mod.py": (
        "import time\n"
        "def heartbeat():\n"
        "    return time.time()\n")}
    assert not _rules_of(live, "sim-wall-clock")
    suppressed_src = {"batch_shipyard_tpu/sim/simulator.py": (
        "import time\n"
        "def run():\n"
        "    return time.time()  "
        "# shipyard-lint: disable=sim-wall-clock\n")}
    active, suppressed = _run(suppressed_src, "sim-wall-clock")
    assert not active and len(suppressed) == 1


# ----------------------------- shell family ----------------------------

def test_shell_strict_mode_fires_without_set_e():
    firing = {"tools/x.sh": "#!/bin/sh\nrm -rf \"$D\"\n"}
    assert len(_rules_of(firing, "shell-strict-mode")) == 1
    blessed = {"tools/x.sh":
               "#!/bin/sh\nset -euo pipefail\nrm -rf \"$D\"\n"}
    assert not _rules_of(blessed, "shell-strict-mode")


def test_shell_unquoted_var_fires_on_path_commands():
    firing = {"tools/x.sh":
              "#!/bin/sh\nset -e\nrm -rf $DIR\n"}
    assert len(_rules_of(firing, "shell-unquoted-var")) == 1
    blessed = {"tools/x.sh": (
        "#!/bin/sh\nset -e\n"
        "rm -rf \"$DIR\"\n"
        "echo \"run: source $VENV/bin/activate\"\n"
        "# rm -rf $COMMENTED\n")}
    assert not _rules_of(blessed, "shell-unquoted-var")


def test_shell_backtick_subst_fires():
    firing = {"tools/x.sh": "#!/bin/sh\nset -e\nTS=`date`\n"}
    assert len(_rules_of(firing, "shell-backtick-subst")) == 1
    blessed = {"tools/x.sh": "#!/bin/sh\nset -e\nTS=$(date)\n"}
    assert not _rules_of(blessed, "shell-backtick-subst")


# ---------------------------- serving family ---------------------------

def test_serving_page_refcount_fires_on_direct_free():
    """Every direct `_free_pages` mutation shape outside the release
    helper fires: mutating method calls, reassignment, item
    assignment, augassign, and del."""
    firing = {"batch_shipyard_tpu/models/mod.py": (
        "class Pool:\n"
        "    def _preempt(self, i):\n"
        "        self._free_pages.extend(self._slot_pages[i])\n"
        "    def reset(self):\n"
        "        self._free_pages = []\n"
        "    def patch(self, k, v):\n"
        "        self._free_pages[k] = v\n"
        "    def grow(self, pages):\n"
        "        self._free_pages += pages\n"
        "    def nuke(self):\n"
        "        del self._free_pages[0]\n")}
    found = _rules_of(firing, "serving-page-refcount")
    assert len(found) == 5, [f.render() for f in found]
    assert "_release_pages" in found[0].message


def test_serving_page_refcount_blessed_shapes_pass():
    """The allowed owners — __init__ seeding, the allocator popping,
    the release helper returning — plus read-only uses stay silent;
    module-level mutation outside a def still fires."""
    blessed = {"batch_shipyard_tpu/models/mod.py": (
        "class Pool:\n"
        "    def __init__(self, n):\n"
        "        self._free_pages = list(range(n))\n"
        "    def _alloc_page(self):\n"
        "        return self._free_pages.pop()\n"
        "    def _release_pages(self, pages):\n"
        "        self._free_pages.extend(pages)\n"
        "    def stats(self):\n"
        "        return len(self._free_pages)\n"
        "    def peek(self):\n"
        "        return list(self._free_pages)\n")}
    assert not _rules_of(blessed, "serving-page-refcount")
    module_level = {"batch_shipyard_tpu/models/mod.py": (
        "pool._free_pages.clear()\n")}
    found = _rules_of(module_level, "serving-page-refcount")
    assert len(found) == 1 and "<module>" in found[0].message
    suppressed_src = {"batch_shipyard_tpu/models/mod.py": (
        "class Pool:\n"
        "    def drain(self):\n"
        "        self._free_pages.clear()  "
        "# shipyard-lint: disable=serving-page-refcount\n")}
    active, suppressed = _run(suppressed_src,
                              "serving-page-refcount")
    assert not active and len(suppressed) == 1


def test_serving_drain_no_admit_fires_on_unchecked_admission():
    """Both admission shapes — firing the on_admit hook and
    submitting into the engine — fire when the enclosing function
    never consults the draining flag."""
    firing = {"batch_shipyard_tpu/models/mod.py": (
        "class Front:\n"
        "    def fast_path(self, req):\n"
        "        self.engine.submit(req)\n"
        "    def seat(self, req):\n"
        "        self.on_admit(req.request_id)\n")}
    found = _rules_of(firing, "serving-drain-no-admit")
    assert len(found) == 2, [f.render() for f in found]
    assert "draining" in found[0].message


def test_serving_drain_no_admit_blessed_shapes_pass():
    """An admission path that checks the draining flag (attribute or
    bare name, anywhere in the function body) stays silent; inline
    suppression works; non-admitting engine calls never fire."""
    blessed = {"batch_shipyard_tpu/models/mod.py": (
        "class Front:\n"
        "    def submit(self, req):\n"
        "        if self.draining:\n"
        "            raise RuntimeError('draining')\n"
        "        self.engine.submit(req)\n"
        "    def seat(self, req, draining):\n"
        "        if draining:\n"
        "            return\n"
        "        self.on_admit(req.request_id)\n"
        "    def stats(self):\n"
        "        return self.engine.stats()\n")}
    assert not _rules_of(blessed, "serving-drain-no-admit")
    suppressed_src = {"batch_shipyard_tpu/models/mod.py": (
        "class Front:\n"
        "    def fast_path(self, req):\n"
        "        self.engine.submit(req)  "
        "# shipyard-lint: disable=serving-drain-no-admit\n")}
    active, suppressed = _run(suppressed_src,
                              "serving-drain-no-admit")
    assert not active and len(suppressed) == 1


# ------------------------------ the gate -------------------------------

def test_repo_is_lint_clean():
    """The tier-1 lint gate: every rule over the real tree, judged
    against the checked-in baseline. New findings fail here exactly
    as `shipyard lint` would fail in CI; stale baseline entries fail
    too, so triage debt only shrinks."""
    report = analysis.analyze()
    assert not report.new, "\n".join(
        f.render() for f in report.new)
    assert not report.stale_baseline, (
        f"baseline lists fixed findings "
        f"{report.stale_baseline}; run "
        f"`shipyard lint --baseline-update`")


def test_repo_baseline_is_fully_triaged():
    """Acceptance: the committed baseline is empty — every finding
    the analyzer raised during this PR was fixed or inline-suppressed
    with a justification, not parked."""
    baseline = analysis.load_baseline(
        core.repo_root() / analysis.BASELINE_FILENAME)
    assert sum(baseline.values()) == 0


def test_action_lint_list_rules_and_gate(capsys):
    """The CLI surface: --list-rules inventories every registered
    rule; a plain run over this tree reports clean; the footgun
    combination --rules + --baseline-update is refused (it would
    rewrite the WHOLE baseline from a partial run, deleting every
    other rule's triaged entries)."""
    from batch_shipyard_tpu import fleet
    payload = fleet.action_lint(None, list_rules=True, raw=True)
    assert len(payload["rules"]) == len(analysis.RULES)
    capsys.readouterr()
    payload = fleet.action_lint(None, raw=True)
    assert payload["clean"] is True
    capsys.readouterr()
    with pytest.raises(ValueError):
        fleet.action_lint(None, baseline_update=True,
                          rules=("store-blind-upsert",))


def test_cli_lint_rejects_unknown_rule_as_usage_error():
    """A typo'd --rules id must read as a usage error (exit 2 with
    the flag named), never as lint findings or a raw traceback."""
    from click.testing import CliRunner

    from batch_shipyard_tpu.cli import main as cli_main
    result = CliRunner().invoke(cli_main.cli,
                                ["lint", "--rules", "bogus-rule"])
    assert result.exit_code == 2
    assert "unknown rule" in result.output
    assert "bogus-rule" in result.output


def test_stale_baseline_fails_cli_gate_too(tmp_path, monkeypatch):
    """Gate parity: a stale baseline entry (finding fixed but still
    listed) must flip the CLI's clean verdict exactly like the tier-1
    pytest gate — the operator and CI can never disagree."""
    import json

    from batch_shipyard_tpu import fleet
    fake_root = tmp_path / "repo"
    (fake_root / "batch_shipyard_tpu").mkdir(parents=True)
    (fake_root / "batch_shipyard_tpu" / "ok.py").write_text("x = 1\n")
    (fake_root / analysis.BASELINE_FILENAME).write_text(json.dumps({
        "version": 1,
        "findings": [{"rule": "store-blind-upsert",
                      "path": "batch_shipyard_tpu/gone.py",
                      "message": "fixed long ago"}]}))
    monkeypatch.setattr(analysis, "repo_root", lambda: fake_root)
    payload = fleet.action_lint(None, raw=True)
    assert payload["clean"] is False
    assert payload["stale_baseline"]

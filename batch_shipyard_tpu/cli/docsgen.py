"""Config reference generator: schemas -> markdown.

The strict schemas are the single source of truth for the config
surface; this renders them as documentation so the reference can never
drift from the validator (the reference maintained 2.4k lines of
schema YAML and separate docs pages by hand).

Usage: python -m batch_shipyard_tpu.cli.docsgen > docs/03-config.md
"""

from __future__ import annotations

import io
import sys

import yaml

from batch_shipyard_tpu.config.validator import _SCHEMA_DIR, ConfigType


def _describe(schema: dict) -> str:
    stype = schema.get("type", "any")
    parts = [stype]
    if "enum" in schema:
        def lit(v):
            # YAML literals, not Python reprs (True -> true); drop
            # the scalar document-end marker safe_dump appends.
            return yaml.safe_dump(
                v, default_flow_style=True).strip().split("\n")[0]
        parts.append("one of: " + ", ".join(
            f"`{lit(v)}`" for v in schema["enum"]))
    if "pattern" in schema:
        parts.append(f"pattern `{schema['pattern']}`")
    if "range" in schema:
        rng = schema["range"]
        bounds = []
        if "min" in rng:
            bounds.append(f">= {rng['min']}")
        if "max" in rng:
            bounds.append(f"<= {rng['max']}")
        parts.append(" and ".join(bounds))
    if schema.get("nullable"):
        parts.append("nullable")
    if schema.get("required"):
        parts.append("**required**")
    return "; ".join(parts)


def _walk(schema: dict, path: str, rows: list[tuple[str, str]]) -> None:
    stype = schema.get("type", "any")
    if stype == "map":
        if schema.get("allow_unknown"):
            rows.append((f"{path}.*", "map (free-form keys)"))
        for key, sub in schema.get("mapping", {}).items():
            _walk(sub, f"{path}.{key}", rows)
    elif stype == "seq":
        elem = schema.get("sequence")
        if elem is not None:
            _walk(elem, f"{path}[]", rows)
        else:
            rows.append((f"{path}[]", "seq"))
    else:
        rows.append((path, _describe(schema)))


def generate() -> str:
    out = io.StringIO()
    out.write(
        "# Configuration reference\n\n"
        "Generated from the strict validation schemas "
        "(`batch_shipyard_tpu/config/schemas/`) — regenerate with\n"
        "`python -m batch_shipyard_tpu.cli.docsgen > "
        "docs/03-config.md`.\n"
        "Unknown keys are rejected at load time.\n")
    for ct in ConfigType:
        with open(_SCHEMA_DIR / f"{ct.value}.yaml", "r",
                  encoding="utf-8") as fh:
            schema = yaml.safe_load(fh)
        out.write(f"\n## {ct.value}.yaml\n\n")
        rows: list[tuple[str, str]] = []
        _walk(schema, "", rows)
        out.write("| Key | Type / constraints |\n|---|---|\n")
        for path, desc in rows:
            out.write(f"| `{path.lstrip('.')}` | {desc} |\n")
    # Hand-maintained nuance lives in docs/_config_notes.md and is
    # appended verbatim: the tables above can regenerate without
    # losing it, and a note about a key the schemas dropped sticks
    # out instead of silently surviving inside a stale table row.
    notes = (_SCHEMA_DIR.parent.parent.parent / "docs"
             / "_config_notes.md")
    if not notes.exists():
        raise FileNotFoundError(
            f"{notes}: the hand-maintained Key notes section is "
            f"required — regenerating without it would silently drop "
            f"documented caveats (incl. the registry-password "
            f"plaintext warning)")
    out.write("\n" + notes.read_text(encoding="utf-8"))
    return out.getvalue()


if __name__ == "__main__":
    sys.stdout.write(generate())

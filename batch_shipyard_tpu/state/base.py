"""State store interface: objects + tables + queues + leases.

The reference's key structural insight (SURVEY.md section 1) is that ALL
coordination between the CLI, the daemons, and the nodes flows through
cloud storage primitives: blobs (+ leases as distributed locks), tables
(+ etag optimistic concurrency), and queues (convoy/storage.py:68
_STORAGE_CONTAINERS; cascade lease gate cascade.py:574-635; federation
queues storage.py:1276). We keep that design and put the primitives
behind one interface so that GCS, a local filesystem, and an in-memory
fake are interchangeable — which is what makes every distributed
protocol in this framework unit-testable without a cloud account
(SURVEY.md section 4 'Implication for the build').

Concurrency semantics:
  - objects carry a monotonically increasing ``generation``; writes and
    deletes accept ``if_generation_match`` (0 = only-if-absent), the GCS
    precondition model.
  - table entities carry an ``etag``; ``merge`` and ``delete`` accept
    ``if_match``.
  - leases are (key, owner, expiry) records acquirable only when free or
    expired; renew/release require the owner token.
  - queue messages have a visibility timeout and a pop receipt, the
    Azure queue model (at-least-once delivery).
"""

from __future__ import annotations

import abc
import dataclasses
import datetime
from typing import Any, Iterable, Iterator, Optional


class NotFoundError(KeyError):
    """Object/entity/message does not exist."""


class PreconditionFailedError(RuntimeError):
    """Generation precondition failed on an object write/delete."""


class EntityExistsError(RuntimeError):
    """Insert of an already-existing table entity."""


class EtagMismatchError(RuntimeError):
    """Entity etag precondition failed."""


class LeaseLostError(RuntimeError):
    """Lease renew/release by a non-owner or after expiry."""


@dataclasses.dataclass(frozen=True)
class ObjectMeta:
    key: str
    size: int
    generation: int
    updated: datetime.datetime


@dataclasses.dataclass(frozen=True)
class LeaseHandle:
    key: str
    owner: str
    token: str
    expires_at: float


@dataclasses.dataclass(frozen=True)
class QueueMessage:
    queue: str
    message_id: str
    pop_receipt: str
    payload: bytes
    dequeue_count: int


class StateStore(abc.ABC):
    """Abstract object/table/queue/lease store."""

    # ------------------------------ objects ----------------------------

    @abc.abstractmethod
    def put_object(self, key: str, data: bytes,
                   if_generation_match: Optional[int] = None) -> int:
        """Write an object; returns its new generation.

        ``if_generation_match=0`` means create-only (fail if exists);
        any other value requires the current generation to match.
        """

    @abc.abstractmethod
    def get_object(self, key: str) -> bytes: ...

    @abc.abstractmethod
    def get_object_meta(self, key: str) -> ObjectMeta: ...

    @abc.abstractmethod
    def delete_object(self, key: str,
                      if_generation_match: Optional[int] = None) -> None: ...

    @abc.abstractmethod
    def list_objects(self, prefix: str = "") -> list[str]: ...

    # Default streaming chunk: large enough to amortize round trips,
    # small enough that a chunk is never a memory concern.
    STREAM_CHUNK_BYTES = 8 * 1024 * 1024

    def put_object_stream(self, key: str, chunks: Iterable[bytes],
                          if_generation_match: Optional[int] = None
                          ) -> int:
        """Write an object from an iterable of byte chunks without the
        caller materializing the whole payload (the blobxfer streaming
        role, reference convoy/data.py:981). Backends with a native
        streaming path override this; the fallback concatenates (the
        memory backend stores the whole buffer anyway)."""
        return self.put_object(key, b"".join(chunks),
                               if_generation_match=if_generation_match)

    def get_object_stream(self, key: str,
                          chunk_size: Optional[int] = None
                          ) -> Iterator[bytes]:
        """Yield an object's bytes in chunks. Fallback reads whole;
        backends with ranged/positional reads override."""
        chunk_size = chunk_size or self.STREAM_CHUNK_BYTES
        data = self.get_object(key)
        for i in range(0, len(data), chunk_size):
            yield data[i:i + chunk_size]

    def object_exists(self, key: str) -> bool:
        try:
            self.get_object_meta(key)
            return True
        except NotFoundError:
            return False

    def generate_signed_url(self, key: str, method: str = "GET",
                            expires_seconds: float = 3600.0) -> str:
        """Time-limited signed URL for one object (the `storage sas
        create` analog, reference shipyard.py:1327 + SAS generation in
        convoy/storage.py). Only cloud backends can mint these; the
        local/memory stores raise a clear error instead of minting a
        URL nobody outside this process could dereference."""
        raise NotImplementedError(
            f"{type(self).__name__} cannot mint signed URLs — "
            f"signed access requires the gcs backend")

    # ------------------------------ leases -----------------------------

    @abc.abstractmethod
    def acquire_lease(self, key: str, duration_seconds: float,
                      owner: str) -> Optional[LeaseHandle]:
        """Try to acquire a named lease; None if currently held."""

    @abc.abstractmethod
    def renew_lease(self, handle: LeaseHandle,
                    duration_seconds: float) -> LeaseHandle:
        """Extend a held lease; raises LeaseLostError if lost."""

    @abc.abstractmethod
    def release_lease(self, handle: LeaseHandle) -> None: ...

    # ------------------------------ tables -----------------------------

    @abc.abstractmethod
    def insert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        """Insert; raises EntityExistsError if present. Returns etag."""

    @abc.abstractmethod
    def upsert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        """Insert or replace unconditionally. Returns etag."""

    @abc.abstractmethod
    def merge_entity(self, table: str, partition_key: str, row_key: str,
                     entity: dict[str, Any],
                     if_match: Optional[str] = None) -> str:
        """Merge keys into an existing entity (optimistic via if_match).

        Raises NotFoundError or EtagMismatchError. Returns new etag.
        """

    @abc.abstractmethod
    def get_entity(self, table: str, partition_key: str,
                   row_key: str) -> dict[str, Any]:
        """Fetch entity; includes ``_etag``, ``_pk``, ``_rk`` keys."""

    @abc.abstractmethod
    def query_entities(self, table: str,
                       partition_key: Optional[str] = None,
                       row_key_prefix: str = "",
                       ) -> Iterator[dict[str, Any]]: ...

    @abc.abstractmethod
    def delete_entity(self, table: str, partition_key: str, row_key: str,
                      if_match: Optional[str] = None) -> None: ...

    def count_entities_by(self, table: str, partition_key: str,
                          column: str = "state") -> dict[str, int]:
        """Server-side group-count of one partition's entities by a
        column value: {value: count}, with rows missing the column
        grouped under "". The terminal-state summary `jobs wait` and
        the bench drain loop poll on — at 10^6 tasks a poll must not
        materialize (or ship) every row just to count states.
        Fallback iterates ``query_entities``; backends override to
        count without building per-row result dicts."""
        counts: dict[str, int] = {}
        for row in self.query_entities(table,
                                       partition_key=partition_key):
            value = str(row.get(column) or "")
            counts[value] = counts.get(value, 0) + 1
        return counts

    # ------------------------------ queues -----------------------------

    @abc.abstractmethod
    def put_message(self, queue: str, payload: bytes,
                    delay_seconds: float = 0.0) -> str: ...

    def put_messages(self, queue: str, payloads: list[bytes],
                     delay_seconds: float = 0.0) -> list[str]:
        """Batch enqueue (the TaskAddCollection-chunking analog,
        reference batch.py:4313). Default loops; backends override to
        amortize locking/round trips."""
        # This IS the batched API's fallback — the per-item loop the
        # store-write-in-loop rule exists to funnel callers toward.
        return [self.put_message(queue, p, delay_seconds)  # shipyard-lint: disable=store-write-in-loop
                for p in payloads]

    def insert_entities(self, table: str,
                        rows: list[tuple[str, str, dict]]) -> list[str]:
        """Batch insert [(pk, rk, entity)]; all-or-error semantics are
        per-row (EntityExistsError aborts at the failing row)."""
        # Batched-API fallback: the one sanctioned per-item loop.
        return [self.insert_entity(table, pk, rk, entity)  # shipyard-lint: disable=store-write-in-loop
                for pk, rk, entity in rows]

    @abc.abstractmethod
    def get_messages(self, queue: str, max_messages: int = 1,
                     visibility_timeout: float = 30.0,
                     ) -> list[QueueMessage]: ...

    @abc.abstractmethod
    def delete_message(self, message: QueueMessage) -> None: ...

    @abc.abstractmethod
    def update_message(self, message: QueueMessage,
                       visibility_timeout: float) -> QueueMessage:
        """Reset a claimed message's visibility timeout (keeps claim)."""

    @abc.abstractmethod
    def queue_length(self, queue: str) -> int: ...

    # --------------------------- lifecycle -----------------------------

    def clear(self) -> None:
        """Remove all state (test/teardown helper)."""
        raise NotImplementedError

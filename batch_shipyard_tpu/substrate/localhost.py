"""Localhost substrate: node agents as real subprocesses on this host.

This is how the framework drives real hardware attached to the current
machine — notably the benchmark path, where a 1-worker 'pool' on this
host runs a JAX training task against the locally visible TPU chip(s)
through the full pool/jobs pipeline. It is also the multi-process
integration substrate for the localfs state store.
"""

from __future__ import annotations


import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Optional

from batch_shipyard_tpu.config.settings import (
    CredentialsSettings, PoolSettings)
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.substrate import base
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class LocalhostSubstrate(base.ComputeSubstrate):
    def __init__(self, store: StateStore,
                 credentials: CredentialsSettings,
                 work_root: Optional[str] = None,
                 pool_config: Optional[dict] = None,
                 run_nodeprep: bool = False) -> None:
        if credentials.storage.backend == "memory":
            raise ValueError(
                "localhost substrate needs a cross-process state store "
                "(localfs or gcs), not memory")
        self.store = store
        self.credentials = credentials
        self.work_root = work_root or tempfile.mkdtemp(prefix="localnode-")
        self.pool_config = pool_config or {}
        self.run_nodeprep = run_nodeprep
        self._procs: dict[str, dict[str, subprocess.Popen]] = {}

    def _spawn_node(self, pool: PoolSettings, slice_index: int,
                    worker_index: int, node_index: int) -> None:
        node_id = f"{pool.id}-local-{node_index}"
        work_dir = os.path.join(self.work_root, pool.id, node_id)
        os.makedirs(work_dir, exist_ok=True)
        boot = {
            "storage": {
                "backend": self.credentials.storage.backend,
                "bucket": self.credentials.storage.bucket,
                "prefix": self.credentials.storage.prefix,
                "root": self.credentials.storage.root,
            },
            "pool_config": self.pool_config,
            "identity": {
                "pool_id": pool.id, "node_id": node_id,
                "node_index": node_index,
                "hostname": socket.gethostname(),
                "internal_ip": "127.0.0.1",
                "slice_index": slice_index,
                "worker_index": worker_index,
            },
            "work_dir": work_dir,
            "heartbeat_interval": 2.0,
            "poll_interval": 0.2,
            "node_stale_seconds": 10.0,
            "run_nodeprep": self.run_nodeprep,
            "output_upload_cap_bytes": (
                pool.output_upload_cap_mb * 1024 * 1024
                if pool.output_upload_cap_mb else None),
        }
        boot_path = os.path.join(work_dir, "bootstrap.json")
        with open(boot_path, "w", encoding="utf-8") as fh:
            json.dump(boot, fh)
        self.store.upsert_entity(
            names.TABLE_NODES, pool.id, node_id, {
                "state": "creating", "hostname": boot["identity"][
                    "hostname"],
                "internal_ip": "127.0.0.1", "node_index": node_index,
                "slice_index": slice_index, "worker_index": worker_index,
                "registered_at": time.time()})
        log = open(os.path.join(work_dir, "agent.log"), "ab")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        # Tasks run with cwd=task_dir; make the framework importable
        # there even when not pip-installed (dev/offline hosts).
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "batch_shipyard_tpu.agent", boot_path],
            stdout=log, stderr=log, cwd=repo_root, env=env)
        self._procs.setdefault(pool.id, {})[node_id] = proc
        logger.info("spawned local node agent %s (pid %d)", node_id,
                    proc.pid)

    def _pool_shape(self, pool: PoolSettings) -> tuple[int, int]:
        if pool.tpu is not None:
            return pool.tpu.num_slices, pool.tpu.workers_per_slice
        return 1, max(1, pool.vm_count_dedicated +
                      pool.vm_count_low_priority)

    def allocate_pool(self, pool: PoolSettings) -> None:
        num_slices, workers = self._pool_shape(pool)
        node_index = 0
        for s in range(num_slices):
            for w in range(workers):
                self._spawn_node(pool, s, w, node_index)
                node_index += 1

    def deallocate_pool(self, pool_id: str) -> None:
        procs = self._procs.pop(pool_id, {})
        for proc in procs.values():
            proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool_id)):
            self.store.delete_entity(names.TABLE_NODES, pool_id, row["_rk"])

    def resize_pool(self, pool: PoolSettings, num_slices: int) -> None:
        raise NotImplementedError(
            "localhost pools are fixed-size; delete and re-add")

    def _stop_slice_nodes(self, pool_id: str,
                          slice_index: int) -> list[dict]:
        """Stop every agent of a slice and return its node rows.
        Agents spawned by THIS process are terminated directly; rows
        without a live in-process handle (fresh CLI attaching to an
        existing pool) get a shutdown control message instead — the
        agent subprocess exits on its next control poll."""
        procs = self._procs.get(pool_id, {})
        rows = [row for row in self.store.query_entities(
            names.TABLE_NODES, partition_key=pool_id)
            if int(row.get("slice_index", -1)) == slice_index]
        for row in rows:
            node_id = row["_rk"]
            proc = procs.pop(node_id, None)
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
            else:
                # Distinct per-node control queue each iteration —
                # nothing to batch.
                self.store.put_message(  # shipyard-lint: disable=store-write-in-loop
                    names.control_queue(pool_id, node_id),
                    json.dumps({"type": "shutdown"}).encode())
                # Wait for the agent's final offline heartbeat so a
                # replacement spawned onto the same node_id cannot
                # race it for the shared control queue (it would eat
                # the shutdown meant for its predecessor).
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline:
                    try:
                        cur = self.store.get_entity(
                            names.TABLE_NODES, pool_id, node_id)
                    except KeyError:
                        break
                    if cur.get("state") == "offline":
                        break
                    time.sleep(0.2)
        return rows

    def recreate_slice(self, pool: PoolSettings, slice_index: int) -> None:
        for row in self._stop_slice_nodes(pool.id, slice_index):
            self._spawn_node(pool, slice_index,
                             int(row.get("worker_index", 0)),
                             int(row.get("node_index", 0)))

    def deallocate_slice(self, pool: PoolSettings,
                         slice_index: int) -> None:
        for row in self._stop_slice_nodes(pool.id, slice_index):
            try:
                self.store.delete_entity(names.TABLE_NODES, pool.id,
                                         row["_rk"])
            except KeyError:
                pass

    def get_remote_login(self, pool_id: str,
                         node_id: str) -> Optional[tuple[str, int]]:
        return "127.0.0.1", 22

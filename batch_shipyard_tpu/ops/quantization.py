"""Int8 quantization kernels: Pallas stochastic-rounding quantize +
int8 MXU matmul.

The v5e MXU runs int8 at 2x the bf16 rate; these kernels provide the
building blocks for int8 serving and quantized training experiments:

  - ``quantize_int8``: per-row absmax scaling with unbiased
    stochastic rounding (floor(x+u) against jax-PRNG random bits —
    the requirement for using quantized grads/weights in training);
  - ``int8_matmul``: [M,K]i8 x [K,N]i8 -> f32 with int32 MXU
    accumulation and per-row/per-column scale application;
  - ``quantized_linear``: x @ w with both sides quantized on the fly;
    custom_vjp keeps the backward in full precision against the
    original operands (standard quantization-aware training recipe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _largest_divisor_block(dim: int, preferred: int,
                           align: int = 128) -> int:
    """Largest divisor of dim that is <= preferred AND a multiple of
    the TPU tile alignment (last dim: 128 lanes; second-to-last: 8/32
    sublanes). Falls back to the whole axis when no aligned divisor
    exists — Mosaic accepts a block equal to the full array dim."""
    block = (min(preferred, dim) // align) * align
    while block >= align:
        if dim % block == 0:
            return block
        block -= align
    return dim


def _quantize_kernel(x_ref, bits_ref, values_ref, scales_ref):
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    scaled = x / scale
    # Unbiased stochastic rounding: floor(x + u), u ~ U[0,1) from
    # caller-supplied random bits (an explicit input so the kernel is
    # identical under the interpreter, where pltpu's in-kernel PRNG
    # yields constant bits; also keeps randomness keyed by jax PRNG
    # semantics). 24 low bits -> f32 (Mosaic lacks uint32->f32).
    u = jax.lax.bitwise_and(
        bits_ref[...], jnp.int32((1 << 24) - 1)
    ).astype(jnp.float32) * (1.0 / (1 << 24))
    rounded = jnp.floor(scaled + u)
    values_ref[...] = jnp.clip(rounded, -127.0, 127.0).astype(jnp.int8)
    scales_ref[...] = scale


def quantize_int8(x, seed: int = 0, block_m: int = 256):
    """Per-row absmax int8 quantization with stochastic rounding.
    x: [M, K] float -> (values [M, K] int8, scales [M, 1] f32).
    Row-blocked grid keeps VMEM bounded for large M."""
    m, k = x.shape
    block_m = _largest_divisor_block(m, block_m, align=8)
    bits = jax.lax.bitcast_convert_type(
        jax.random.bits(jax.random.PRNGKey(seed), (m, k),
                        jnp.uint32), jnp.int32)
    return pl.pallas_call(
        _quantize_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m, k), jnp.int8),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
        ),
        grid=(m // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((block_m, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
    )(x, bits)


def quantize_int8_rows(x, eps: float = 1e-8):
    """Plain-jnp absmax row quantization over the LAST axis:
    x [..., D] -> (int8 rows, fp32 scales [...]). The jnp contract
    partner of dequantize_int8 (the Pallas kernels implement the same
    formula with stochastic rounding for training)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, eps) / 127.0
    rows = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return rows.astype(jnp.int8), scale


def dequantize_int8(values, scales):
    return values.astype(jnp.float32) * scales


def _int8_matmul_kernel(x_ref, xs_ref, w_ref, ws_ref, o_ref):
    acc = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # Row scales of x broadcast over rows; column scales of w over
    # columns (w is quantized per-row of w^T == per-column of w).
    o_ref[...] = (acc.astype(jnp.float32) * xs_ref[...] *
                  ws_ref[...].T)


def int8_matmul(x_q, x_scales, w_q, w_scales,
                block_m: int = 512, block_n: int = 512):
    """[M,K]i8 @ [K,N]i8 -> [M,N]f32 with int32 MXU accumulation.
    w_scales: [N, 1] (per output column, from quantizing w^T rows).
    Grid over (M, N) tiles with K resident per program."""
    m, k = x_q.shape
    _, n = w_q.shape
    block_m = _largest_divisor_block(m, block_m, align=8)
    block_n = _largest_divisor_block(n, block_n, align=128)
    return pl.pallas_call(
        _int8_matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=(m // block_m, n // block_n),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
    )(x_q, x_scales, w_q, w_scales)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_linear(x, w, seed: int = 0):
    """x [M,K] @ w [K,N] with both sides int8-quantized on the fly;
    full-precision backward (QAT straight-through). The quantize
    kernel casts to fp32 internally, so bf16 operands pass through
    without materializing an fp32 copy in HBM."""
    x_q, x_s = quantize_int8(x, seed)
    w_q, w_s = quantize_int8(w.T, seed + 1)
    return int8_matmul(x_q, x_s, w_q.T, w_s)


def _ql_fwd(x, w, seed):
    return quantized_linear(x, w, seed), (x, w)


def _ql_bwd(seed, residuals, g):
    x, w = residuals
    g = g.astype(jnp.float32)
    dx = (g @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = (x.astype(jnp.float32).T @ g).astype(w.dtype)
    return dx, dw


quantized_linear.defvjp(_ql_fwd, _ql_bwd)

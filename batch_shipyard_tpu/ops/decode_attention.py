"""Pallas dense decode-attention kernel with in-kernel int8 dequant.

The dense int8 KV decode path (models/transformer._decode_attend)
previously dequantized the ENTIRE [B, L, H, D] cache with an
elementwise multiply outside any kernel and bet peak HBM on XLA fusing
it into the attention dots — the paged path (ops/paged_attention.py)
already dequantizes per tile inside its kernel. This kernel closes the
gap for the dense cache: the int8 K/V rows and their per-(position,
head) fp32 scales stream through VMEM tile by tile, the dequant
multiply happens on the tile right before the dots, and HBM holds
int8 + scales only — the entire 2x-HBM claim of kv_cache_dtype='int8'
(arxiv 2605.25645 makes that headroom the serving-throughput lever).
tools/tpu_checks.py asserts the claim on the COMPILED step: no
full-cache-sized f32/bf16 buffer in the HLO, kernel custom-call
present (check names dense_decode_int8 / dense_decode_hlo).

Shares the online-softmax block recurrence with the paged kernel
(_accumulate_page / _init_and_emit) — a fix there lands here too. The
grid is (batch, heads, length-blocks): blocks wholly past a slot's
live length are skipped (@pl.when) and their DMAs clamped to the last
live block, exactly the paged kernel's dead-step discipline.

impl='auto' (None) resolution is gated by silicon validation: the
kernel turns on only when KERNEL_VALIDATION.json records an on-chip
pass for 'dense_decode_int8' (ops/kernel_select), the XLA
dequant+einsum formulation remaining the reference/fallback path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from batch_shipyard_tpu.ops import kernel_select
from batch_shipyard_tpu.ops.paged_attention import (_accumulate_page,
                                                    _init_and_emit)

_NEG_INF = -1e30


def _dense_decode_kernel_int8(len_ref, q_ref, k_ref, ks_ref, v_ref,
                              vs_ref, o_ref, o_acc, m_acc, l_acc, *,
                              block: int, scale: float):
    """One (slot, head, length-block) program: dequantize the int8
    K/V tile in VMEM ([block, D] int8 * [block, 1] fp32 scales) right
    before the dots, then run the shared online-softmax recurrence."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_blocks = pl.num_programs(2)
    length = len_ref[b]
    emit = _init_and_emit(j, num_blocks, o_ref, o_acc, m_acc, l_acc)

    @pl.when(j * block < length)
    def _accumulate():
        k_tile = k_ref[...].astype(jnp.float32) * ks_ref[...]
        v_tile = v_ref[...].astype(jnp.float32) * vs_ref[...]
        _accumulate_page(q_ref[...].astype(jnp.float32), k_tile,
                         v_tile, j, length, o_acc, m_acc, l_acc,
                         page=block, scale=scale)

    pl.when(j == num_blocks - 1)(emit)


def _largest_block(length: int, preferred: int = 128) -> int:
    """Largest divisor of the cache length <= preferred."""
    block = min(preferred, length)
    while length % block:
        block -= 1
    return block


def dense_decode_attention_kernel(q, cache_k, cache_v, k_scales,
                                  v_scales, lengths,
                                  block: Optional[int] = None,
                                  interpret: bool = False):
    """Pallas path. q: [B, 1, H, D]; cache_k/cache_v: [B, L, H, D]
    int8; k_scales/v_scales: [B, L, H] fp32 per-(position, head)
    absmax scales; lengths: [B] int32 valid-key counts (INCLUDING the
    token written this step — the decode contract never attends an
    unwritten slot). Returns [B, 1, H, D] in q.dtype."""
    batch, seq, heads, depth = q.shape
    assert seq == 1, "dense decode kernel consumes one token per call"
    t_len = cache_k.shape[1]
    block = block or _largest_block(t_len)
    if t_len % block:
        raise ValueError(
            f"cache length {t_len} not divisible by block {block}")
    num_blocks = t_len // block
    scale = 1.0 / (depth ** 0.5)
    q_r = q.reshape(batch, heads, 1, depth)

    def tile_index(b, h, j, ln):
        # Clamp dead steps to the slot's LAST live block: blocks past
        # the length are skipped by @pl.when, so don't spend HBM
        # bandwidth DMA-ing rows nobody reads (the paged kernel's
        # discipline; here every row exists, so this is thrift, not
        # correctness).
        live = jnp.maximum((ln[b] + block - 1) // block - 1, 0)
        return (b, jnp.minimum(j, live), h, 0)

    tile_spec = pl.BlockSpec((None, block, None, depth), tile_index)
    scale_spec = pl.BlockSpec((None, block, None, 1), tile_index)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, heads, num_blocks),
        in_specs=[
            pl.BlockSpec((None, None, 1, depth),
                         lambda b, h, j, ln: (b, h, 0, 0)),
            tile_spec,
            scale_spec,
            tile_spec,
            scale_spec,
        ],
        out_specs=pl.BlockSpec((None, None, 1, depth),
                               lambda b, h, j, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, depth), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_dense_decode_kernel_int8, block=block,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, heads, 1, depth),
                                       q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q_r, cache_k,
      k_scales.reshape(*k_scales.shape, 1), cache_v,
      v_scales.reshape(*v_scales.shape, 1))
    return out.transpose(0, 2, 1, 3)  # [B, 1, H, D]


def dense_decode_attention_xla(q, cache_k, cache_v, k_scales,
                               v_scales, lengths):
    """The reference formulation: dequantize the gathered cache with
    an elementwise multiply and rely on XLA fusing it into the dots —
    the fallback path and the numerics oracle for the kernel. Same
    math as the einsum path in models/transformer._decode_attend."""
    batch, seq, heads, depth = q.shape
    assert seq == 1
    k_all = cache_k.astype(jnp.float32) * k_scales[..., None]
    v_all = cache_v.astype(jnp.float32) * v_scales[..., None]
    k_all = k_all.astype(q.dtype)
    v_all = v_all.astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_all,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(depth))
    key_pos = jax.lax.broadcasted_iota(
        jnp.int32, (cache_k.shape[1], 1), 0)[:, 0]
    mask = key_pos[None, :] < lengths[:, None]
    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v_all,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def resolve_dense_decode_impl(impl: Optional[str] = None) -> str:
    """'kernel' | 'xla' | None (auto). Auto stays on the XLA path
    until tools/tpu_checks.py records an on-chip pass for
    dense_decode_int8 in KERNEL_VALIDATION.json AND the current
    backend is tpu (ops/kernel_select)."""
    if impl is not None:
        if impl not in ("kernel", "xla"):
            raise ValueError(
                f"unknown dense decode attention impl {impl!r}")
        return impl
    return kernel_select.resolve_auto("dense_decode_int8",
                                      pallas_impl="kernel",
                                      fallback="xla")


def dense_decode_attention(q, cache_k, cache_v, k_scales, v_scales,
                           lengths, impl: Optional[str] = None,
                           interpret: bool = False):
    """Dispatch: the in-kernel int8 dequant path or the XLA
    dequant+einsum reference (see resolve_dense_decode_impl)."""
    impl = resolve_dense_decode_impl(impl)
    if impl == "kernel":
        return dense_decode_attention_kernel(
            q, cache_k, cache_v, k_scales, v_scales, lengths,
            interpret=interpret)
    return dense_decode_attention_xla(
        q, cache_k, cache_v, k_scales, v_scales, lengths)

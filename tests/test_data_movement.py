"""Data movement tests: object ingress/egress, sharded transfer
planning, task input/output staging (reference data.py behaviors)."""

import os

import pytest

from batch_shipyard_tpu.data import movement
from batch_shipyard_tpu.state.memory import MemoryStateStore


@pytest.fixture()
def tree(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("aaa")
    (src / "b.dat").write_text("b" * 100)
    (src / "sub" / "c.txt").write_text("ccc")
    return src


def test_ingress_egress_roundtrip(tree, tmp_path):
    store = MemoryStateStore()
    count = movement.ingress_to_storage(store, str(tree), "ing/data")
    assert count == 3
    assert store.get_object("ing/data/a.txt") == b"aaa"
    assert store.get_object("ing/data/sub/c.txt") == b"ccc"
    out = tmp_path / "out"
    assert movement.egress_from_storage(store, "ing/data", str(out)) == 3
    assert (out / "sub" / "c.txt").read_text() == "ccc"


def test_ingress_include_exclude(tree):
    store = MemoryStateStore()
    count = movement.ingress_to_storage(
        store, str(tree), "f", include=["*.txt", "sub/*"],
        exclude=["sub/c.txt"])
    assert count == 1
    assert store.list_objects("f/") == ["f/a.txt"]


def test_multinode_transfer_plan_balances_by_size():
    files = [(f"f{i}", size) for i, size in
             enumerate([100, 90, 50, 40, 30, 10])]
    nodes = [("n0", "10.0.0.1", 22), ("n1", "10.0.0.2", 22)]
    plan = movement.plan_multinode_transfer(files, nodes, "/data")
    assert len(plan) == 2
    loads = {c.node_id: c.total_bytes for c in plan}
    # greedy largest-first: n0 gets 100+40+30=170? check balance < 2x
    assert abs(loads["n0"] - loads["n1"]) <= 100
    all_files = [f for c in plan for f in c.files]
    assert sorted(all_files) == sorted(f for f, _ in files)
    # scp command shape
    assert plan[0].argv[0] == "scp"
    assert plan[0].argv[-1].endswith(":/data")


def test_multinode_transfer_rsync():
    plan = movement.plan_multinode_transfer(
        [("x", 1)], [("n0", "1.2.3.4", 2222)], "/dst", method="rsync",
        ssh_username="me", ssh_private_key="/k")
    argv = plan[0].argv
    assert argv[0] == "rsync"
    assert "me@1.2.3.4:/dst" in argv
    assert any("-p 2222" in a for a in argv)


def test_stage_task_inputs_single_and_prefix(tmp_path):
    store = MemoryStateStore()
    store.put_object("in/one.txt", b"1")
    store.put_object("ds/x/a", b"a")
    store.put_object("ds/x/b/c", b"bc")
    task_dir = tmp_path / "task"
    movement.stage_task_inputs(store, [
        {"kind": "statestore", "key": "in/one.txt",
         "file_path": "one.txt"},
        {"kind": "statestore", "key": "ds/x", "file_path": "data"},
    ], str(task_dir))
    assert (task_dir / "one.txt").read_bytes() == b"1"
    assert (task_dir / "data" / "a").read_bytes() == b"a"
    assert (task_dir / "data" / "b" / "c").read_bytes() == b"bc"


def test_collect_task_outputs(tmp_path):
    store = MemoryStateStore()
    task_dir = tmp_path / "task"
    (task_dir / "results").mkdir(parents=True)
    (task_dir / "results" / "out.npy").write_text("x")
    (task_dir / "stdout.txt").write_text("log")
    count = movement.collect_task_outputs(
        store, [{"include": "results/*"}], str(task_dir),
        "p", "j", "t")
    assert count == 1
    keys = store.list_objects("taskdata/p/j/t/outputs")
    assert keys == ["taskdata/p/j/t/outputs/results/out.npy"]


def test_task_input_data_e2e():
    """Full path: object in store -> input_data -> task reads it."""
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    store.put_object("inputs/greeting.txt", b"hello-from-storage")
    substrate = FakePodSubstrate(store)
    conf = {"pool_specification": {
        "id": "dp", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    pool = S.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings({}), conf)
        jobs = S.job_settings_list({"job_specifications": [{
            "id": "dj",
            "tasks": [{
                "command": "cat greeting.txt",
                "input_data": [{"kind": "statestore",
                                "key": "inputs/greeting.txt",
                                "file_path": "greeting.txt"}],
                "output_data": [{"include": "*.out"}],
            }],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "dp", "dj", timeout=30)
        assert tasks[0]["state"] == "completed"
        out = jobs_mgr.get_task_output(store, "dp", "dj", "task-00000")
        assert out.strip() == b"hello-from-storage"
    finally:
        substrate.stop_all()

"""Point-to-point latency/bandwidth microbenchmark: the OSU
micro-benchmarks (osu_latency / osu_bw) analog for the TPU fabric.

Reference analog: the OSU-flavored MPI recipes
(`/root/reference/recipes/` mpiBench/IntelMPI PingPong lineage) measure
point-to-point latency and bandwidth over Infiniband. On TPU the
point-to-point primitive is `lax.ppermute` over an ICI ring: a
ping-pong is one hop to the right neighbor and one hop back, timed
over a message-size sweep — small sizes expose per-hop latency, large
sizes asymptote to per-link bandwidth.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.p2p_bench \
        --sizes 256,4096,65536,1048576,16777216 --iters 50
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.utils.compat import shard_map


def p2p_pingpong(mesh: Mesh, axis: str, size_bytes: int,
                 iters: int = 50, dtype=jnp.bfloat16) -> dict:
    """Time a neighbor ping-pong (right hop + back) of size_bytes per
    device over the mesh axis. Returns {size_bytes, avg_pingpong_us,
    half_roundtrip_us, bus_gbps}."""
    n = mesh.shape[axis]
    if n < 2:
        raise ValueError(f"p2p needs >= 2 devices on axis {axis!r}")
    elems = max(size_bytes // jnp.dtype(dtype).itemsize, 1)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [((i + 1) % n, i) for i in range(n)]

    def body(x):
        # Chained ping-pong: the return hop depends on the outgoing
        # one, so XLA cannot elide or overlap them away; +1.0 defeats
        # common-subexpression reuse across iterations inside jit.
        y = jax.lax.ppermute(x, axis, fwd)
        return jax.lax.ppermute(y, axis, bwd) + 1.0

    spec = P(axis)
    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=spec, out_specs=spec))
    x = jnp.ones((n, elems), dtype)
    x = fn(x)  # compile + warm
    x.block_until_ready()
    start = time.perf_counter()
    for _ in range(iters):
        x = fn(x)
    x.block_until_ready()
    elapsed = time.perf_counter() - start
    pingpong_s = elapsed / iters
    payload = elems * jnp.dtype(dtype).itemsize
    return {
        "op": "pingpong", "size_bytes": int(payload),
        "avg_pingpong_us": pingpong_s * 1e6,
        "half_roundtrip_us": pingpong_s * 1e6 / 2.0,
        # Two hops move the payload twice per iteration.
        "bus_gbps": 2.0 * payload / pingpong_s / 1e9,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--sizes", default="256,4096,65536,1048576,16777216",
        help="comma-separated per-device message sizes in bytes")
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--dtype", default="bfloat16")
    args = parser.parse_args()

    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.workloads import distributed

    ctx = distributed.setup()
    n_dev = jax.device_count()
    if n_dev < 2:
        distributed.log(ctx, "single device: p2p bench needs >= 2")
        return 0
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    for size in (int(s) for s in args.sizes.split(",")):
        row = p2p_pingpong(mesh, "dp", size, iters=args.iters,
                           dtype=getattr(jnp, args.dtype))
        if jax.process_index() == 0:
            print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Chaos drill scenario runner: prove the self-healing layer.

Runs one or more seeded fault schedules (chaos/plan.py) against a
self-contained fakepod pool (real NodeAgents over a shared in-memory
state store — no cloud, no accelerator) and asserts the recovery
invariants after every drill:

  * every task completed exactly once (bounded retries beat wedges,
    mid-run kills, node preemptions, heartbeat blackouts, store
    faults),
  * no orphaned coordination state (gang rows, queue messages),
  * the goodput partition stayed exact (productive + badput +
    overlapped == wall — chaos moves seconds between categories but
    can never create or lose any).

With --verify-determinism, the same seed is planned twice and the
schedule fingerprints must match — the reproducibility contract that
makes "drill seed 7 regressed" a meaningful bug report.

Exit code 0 means every drill healed; nonzero IS the regression
signal (CI-friendly, same contract as `shipyard chaos drill`).

Usage:
  python tools/chaos_drill.py                       # default scenario
  python tools/chaos_drill.py --seeds 1,2,3         # replay suite
  python tools/chaos_drill.py --kinds task_wedge,node_preempt
  python tools/chaos_drill.py --report-out DRILL.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from batch_shipyard_tpu.chaos import drill  # noqa: E402
from batch_shipyard_tpu.chaos.plan import (  # noqa: E402
    ChaosPlan, INJECTION_KINDS)


def run_scenario(seed: int, tasks: int, duration: float,
                 kinds, injections_per_kind: int,
                 verify_determinism: bool) -> dict:
    entry: dict = {"seed": seed}
    if verify_determinism:
        first = ChaosPlan.generate(seed, duration=duration,
                                   kinds=kinds,
                                   injections_per_kind=injections_per_kind)
        second = ChaosPlan.generate(seed, duration=duration,
                                    kinds=kinds,
                                    injections_per_kind=injections_per_kind)
        entry["determinism"] = (first.fingerprint()
                                == second.fingerprint())
        if not entry["determinism"]:
            entry["status"] = "failed"
            entry["error"] = (
                f"plan fingerprints diverged for seed {seed}: "
                f"{first.fingerprint()} != {second.fingerprint()}")
            return entry
    started = time.monotonic()
    try:
        report = drill.run_drill(
            seed=seed, tasks=tasks, duration=duration, kinds=kinds,
            injections_per_kind=injections_per_kind)
    except AssertionError as exc:
        entry["status"] = "failed"
        entry["error"] = f"invariant violated: {exc}"
        return entry
    except Exception as exc:  # noqa: BLE001 - report, don't die
        entry["status"] = "error"
        entry["error"] = str(exc)
        return entry
    entry.update({
        "status": "ok",
        "fingerprint": report["fingerprint"],
        "wall_seconds": round(time.monotonic() - started, 2),
        "injections_applied": sum(
            1 for a in report["applied"] if a.get("applied")),
        "invariants": report["invariants"],
        "goodput": report.get("goodput", {}),
    })
    return entry


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Seeded chaos drills over a fakepod pool, "
                    "asserting the self-healing invariants")
    parser.add_argument("--seeds", default="0",
                        help="Comma-separated drill seeds")
    parser.add_argument("--tasks", type=int, default=16,
                        help="Tasks per drill")
    parser.add_argument("--duration", type=float, default=4.0,
                        help="Injection window per drill (seconds)")
    parser.add_argument("--kinds", default="",
                        help="Comma-separated injection kinds "
                             f"(default: all of {INJECTION_KINDS})")
    parser.add_argument("--injections-per-kind", type=int, default=1)
    parser.add_argument("--no-verify-determinism",
                        action="store_true",
                        help="Skip the same-seed fingerprint check")
    parser.add_argument("--report-out", default=None,
                        help="Write the full drill report JSON here")
    args = parser.parse_args(argv)

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    kinds = tuple(k.strip() for k in args.kinds.split(",")
                  if k.strip()) or None
    results = []
    for seed in seeds:
        print(f"[chaos-drill] seed {seed}: running "
              f"({args.tasks} tasks, {args.duration}s window)")
        entry = run_scenario(
            seed, args.tasks, args.duration, kinds,
            args.injections_per_kind,
            verify_determinism=not args.no_verify_determinism)
        status = entry["status"]
        detail = (f"applied={entry.get('injections_applied')} "
                  f"retries={entry.get('invariants', {}).get('retries')}"
                  if status == "ok" else entry.get("error", ""))
        print(f"[chaos-drill] seed {seed}: {status} {detail}")
        results.append(entry)

    report = {"scenarios": results,
              "ok": all(r["status"] == "ok" for r in results)}
    if args.report_out:
        with open(args.report_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
        print(f"[chaos-drill] report: {args.report_out}")
    print(f"[chaos-drill] {'HEALED' if report['ok'] else 'FAILED'}: "
          f"{sum(r['status'] == 'ok' for r in results)}/{len(results)}"
          f" drills recovered")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Pool-wide compile-cache seeding through the state store.

The image-prefetch pattern (agent/cascade.py) applied to compiled
executables: after a task, the node agent exports each of its cache
root's identity subdirs as a tar artifact — lease-guarded so exactly
one node uploads per identity — and before the next task every node
seeds from them. First node compiles cold; the other N-1 nodes and
every restart deserialize warm.

Keys (state/names.py):

  * ``compilecache/{pool}/{identity}.tar`` — one identity subdir's
    tar (entries + the manager sidecars, so cold-compile times
    travel).
  * ``compilecache/{pool}/latest.json``    — a PER-IDENTITY map
    ``{"identities": {id: {key, entries, bytes, node_id,
    updated_at}}}``, read first so a node can refuse or skip WITHOUT
    downloading, and so a mixed pool (several workload types = several
    identities) keeps every seed live instead of thrashing one
    pointer.

Transport honesty: XLA's own entry keys make a foreign artifact safe
(it can only miss), but shipping one is pure waste — so seeding
refuses (logs, never raises) an identity the caller pinned that the
pool doesn't hold, artifacts land only in their own identity subdir,
and export refuses to overwrite a newer artifact with a smaller one.
"""

from __future__ import annotations

import json
import os
import tarfile
import tempfile
from typing import Iterator, Optional

from batch_shipyard_tpu.compilecache import manager
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    NotFoundError, PreconditionFailedError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Export is a post-task nicety, not a task phase: keep the lease short
# so a crashed uploader never blocks the pool for long.
EXPORT_LEASE_SECONDS = 120.0

# seed_cache outcomes. Distinct so callers can latch on the durable
# outcomes (SEEDED/REFUSED/SKIP/ABSENT won't change until the pool
# artifacts do) but retry after ERROR (a transient store hiccup must
# not leave a node cold forever).
SEEDED = "seeded"
ABSENT = "absent"      # nothing published for the pool (or identity)
REFUSED = "refused"    # pinned identity not published — would miss
SKIP = "skip"          # local dirs already at least as warm
ERROR = "error"        # transient failure; worth retrying


def latest_info(store: StateStore, pool_id: str) -> Optional[dict]:
    """The pool's seed map ``{"identities": {...}}``, or None."""
    try:
        raw = store.get_object(names.compile_cache_latest_key(pool_id))
        info = json.loads(raw.decode("utf-8"))
        if isinstance(info, dict) and \
                isinstance(info.get("identities"), dict):
            return info
        return None
    except (NotFoundError, ValueError):
        return None


def _tar_chunks(cache_dir: str, entries: dict[str, int]
                ) -> Iterator[bytes]:
    """Stream one identity dir as a tar without materializing it: tar
    into a spooled temp file, then yield store-sized chunks."""
    with tempfile.SpooledTemporaryFile(
            max_size=32 * 1024 * 1024) as spool:
        with tarfile.open(fileobj=spool, mode="w") as tar:
            members = list(entries) + [
                name for name in manager._SIDECARS
                if os.path.exists(os.path.join(cache_dir, name))]
            for name in members:
                tar.add(os.path.join(cache_dir, name), arcname=name)
        spool.seek(0)
        while True:
            chunk = spool.read(StateStore.STREAM_CHUNK_BYTES)
            if not chunk:
                return
            yield chunk


def _update_latest(store: StateStore, pool_id: str, identity: str,
                   record: dict, attempts: int = 5) -> Optional[int]:
    """Read-modify-write one identity's record into the pool map
    under a generation precondition (two nodes exporting DIFFERENT
    identities concurrently must not clobber each other's pointer).
    Returns the new latest.json generation, or None."""
    key = names.compile_cache_latest_key(pool_id)
    for _ in range(attempts):
        try:
            meta = store.get_object_meta(key)
            current = latest_info(store, pool_id) or {"identities": {}}
            precondition = meta.generation
        except NotFoundError:
            current = {"identities": {}}
            precondition = 0  # create-only
        current.setdefault("identities", {})[identity] = record
        try:
            return store.put_object(
                key, json.dumps(current).encode("utf-8"),
                if_generation_match=precondition)
        except PreconditionFailedError:
            continue
    logger.warning("compile cache latest.json update lost the "
                   "precondition race %d times for pool %s",
                   attempts, pool_id)
    return None


def export_cache(store: StateStore, pool_id: str, cache_root: str,
                 owner: str) -> Optional[int]:
    """Upload every identity subdir of the node's cache root that is
    newer than the pool's artifact. Returns the generation of the
    latest.json this node wrote (the caller's seed probe can latch on
    it — it covers everything this node just uploaded), or None when
    nothing was exported. Never raises."""
    generation: Optional[int] = None
    try:
        latest = latest_info(store, pool_id) or {"identities": {}}
        for identity, cache_dir in sorted(
                manager.list_identity_dirs(cache_root).items()):
            if manager.read_identity(cache_dir) != identity:
                continue  # unstamped/corrupt subdir: not exportable
            entries = manager.snapshot(cache_dir)
            if not entries:
                continue
            published = latest["identities"].get(identity) or {}
            if int(published.get("entries", 0)) >= len(entries):
                continue
            lease = store.acquire_lease(
                names.compile_cache_lease_key(pool_id, identity),
                EXPORT_LEASE_SECONDS, owner)
            if lease is None:
                continue
            try:
                key = names.compile_cache_key(pool_id, identity)
                store.put_object_stream(
                    key, _tar_chunks(cache_dir, entries))
                written = _update_latest(store, pool_id, identity, {
                    "key": key,
                    "entries": len(entries),
                    "bytes": sum(entries.values()),
                    "node_id": owner,
                    "updated_at": util.datetime_utcnow_iso(),
                })
                if written is not None:
                    generation = written
                logger.info(
                    "exported compile cache seed for pool %s: %d "
                    "entries, %d bytes (identity %s)", pool_id,
                    len(entries), sum(entries.values()), identity)
            finally:
                try:
                    store.release_lease(lease)
                except Exception:  # noqa: BLE001 - expiry races
                    pass
        return generation
    except Exception:  # noqa: BLE001 - seeding must never fail work
        logger.warning("compile cache export failed for pool %s",
                       pool_id, exc_info=True)
        return generation


def _safe_extract(tar: tarfile.TarFile, cache_dir: str) -> int:
    """Extract flat regular members only; reject traversal. Existing
    files are kept (the node's own entries are never clobbered by a
    seed)."""
    count = 0
    for member in tar.getmembers():
        name = member.name
        if (not member.isfile() or name.startswith(("/", "..")) or
                "/" in name or "\\" in name):
            logger.warning("compile cache seed: skipping suspicious "
                           "tar member %r", name)
            continue
        target = os.path.join(cache_dir, name)
        if os.path.exists(target):
            continue
        src = tar.extractfile(member)
        if src is None:
            continue
        # tmp + rename: the dir is LIVE — a concurrently running
        # task's persistent-cache lookup must never read a
        # half-written executable.
        tmp = target + ".seedtmp"
        with open(tmp, "wb") as dst:
            dst.write(src.read())
        os.replace(tmp, target)
        count += 1
    return count


def _seed_one(store: StateStore, record: dict,
              cache_dir: str) -> bool:
    """Download one identity's artifact (streamed to a spooled temp
    file, never fully in memory — real pod caches run to GBs) and
    extract the entries the local subdir is missing."""
    os.makedirs(cache_dir, exist_ok=True)
    local = manager.snapshot(cache_dir)
    if len(local) >= int(record.get("entries", 0)):
        return False
    with tempfile.SpooledTemporaryFile(
            max_size=32 * 1024 * 1024) as spool:
        for chunk in store.get_object_stream(record["key"]):
            spool.write(chunk)
        spool.seek(0)
        with tarfile.open(fileobj=spool, mode="r") as tar:
            return _safe_extract(tar, cache_dir) > 0


def seed_cache(store: StateStore, pool_id: str, cache_root: str,
               expected_identity: Optional[str] = None) -> str:
    """Populate ``cache_root``'s identity subdirs from the pool's
    artifacts; returns one of the outcome constants above (never
    raises). ``expected_identity`` pins ONE identity — refused (with
    a log) when the pool doesn't publish it; without a pin every
    published identity seeds its own subdir (a mixed pool's next
    workload type finds its cache warm too)."""
    try:
        latest = latest_info(store, pool_id)
        if latest is None:
            return ABSENT
        identities = latest.get("identities", {})
        if expected_identity is not None:
            if expected_identity not in identities:
                logger.warning(
                    "compile cache seed for pool %s refused: no "
                    "artifact for identity %s (published: %s) — "
                    "jax/jaxlib/device/topology/model differ",
                    pool_id, expected_identity,
                    sorted(identities) or "none")
                return REFUSED
            identities = {
                expected_identity: identities[expected_identity]}
        seeded = 0
        for identity, record in sorted(identities.items()):
            if not isinstance(record, dict) or not record.get("key"):
                continue
            if _seed_one(store, record,
                         manager.identity_subdir(cache_root,
                                                 identity)):
                seeded += 1
                logger.info("seeded compile cache for pool %s "
                            "(identity %s)", pool_id, identity)
        if seeded:
            return SEEDED
        return SKIP if identities else ABSENT
    except NotFoundError:
        return ABSENT
    except Exception:  # noqa: BLE001 - seeding must never fail work
        logger.warning("compile cache seed failed for pool %s",
                       pool_id, exc_info=True)
        return ERROR


def prune(store: StateStore, pool_id: str) -> int:
    """Delete the pool's cache artifacts (the stale-cache escape
    hatch: ``shipyard pool cache prune`` after a jax upgrade or model
    change leaves nothing for nodes to mis-seed from). Returns the
    number of objects removed."""
    removed = 0
    for key in store.list_objects(f"compilecache/{pool_id}/"):
        try:
            store.delete_object(key)
            removed += 1
        except NotFoundError:
            pass
    return removed


def stats(store: StateStore, pool_id: str) -> dict:
    """The pool's seed state for ``shipyard pool cache stats``."""
    latest = latest_info(store, pool_id)
    artifacts = store.list_objects(f"compilecache/{pool_id}/")
    return {
        "pool_id": pool_id,
        "identities": (latest or {}).get("identities", {}),
        "artifacts": sorted(a for a in artifacts
                            if a.endswith(".tar")),
    }

"""Hot-loop rules: work that runs once per heartbeat must stay cheap.

The node agent's heartbeat thread drives every sweep
(agent/node_agent.py _heartbeat_loop): retention, orphaned-gang
janitor, preemption sweep, request forwarding. Anything slow or
store-heavy inside that path multiplies by pool size and by heartbeat
rate — the PR 10 review settled the discipline: unpartitioned table
scans are allowed only behind a leader gate (today the lease-backed
_sweep_leader_epoch; historically _is_gang_sweep_leader), so a pool
pays ONE scan per interval, not one per node; and a sweep must never
sleep (a blocked sweep starves the heartbeat itself, and a
heartbeat-stale node gets its running tasks reclaimed as orphans).
Since PR 13 the gate must be a NAMED LEASE with a fencing epoch
(leader-sweep-no-lease): heartbeat-freshness elections have a
double-leader window that fences nothing.
"""

from __future__ import annotations

import ast
import re

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, keyword_arg, rule)

# Functions that run on the heartbeat cadence: the sweep/heartbeat
# naming convention is load-bearing (the existing sweeps all follow
# it), so the rule keys on it.
_HOT_NAME_RE = re.compile(r"(^|_)(sweep|heartbeat)(_|$)")


def _is_hot(fn: ast.FunctionDef) -> bool:
    return bool(_HOT_NAME_RE.search(fn.name))


def _leader_gated(fn: ast.FunctionDef) -> bool:
    """A call to a leadership helper anywhere in the function body
    (the _sweep_leader_epoch idiom; the deleted
    _is_gang_sweep_leader also matched) marks the whole function as
    one-scan-per-pool."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and "leader" in name:
                return True
    return False


@rule("loop-unpartitioned-scan", family="loop")
def check_unpartitioned_scan(ctx: AnalysisContext) -> list[Finding]:
    """``query_entities`` with no partition key inside a
    heartbeat/sweep function that is not leader-gated: every node in
    the pool pays a full-table scan per heartbeat, so store load
    scales as nodes x rows x rate.

    Provenance: the PR 5 orphaned-gang janitor originally scanned
    the gang table from EVERY node each heartbeat; the PR 10 review
    leader-gated it (one unpartitioned scan per pool per interval)
    and the preemption sweep was born gated. New sweeps must follow
    the precedent or partition the scan."""
    findings = []
    for src in ctx.python_files:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if not _is_hot(fn) or _leader_gated(fn):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and
                        call_name(node) == "query_entities"):
                    continue
                pk = (keyword_arg(node, "partition_key")
                      or (node.args[1] if len(node.args) > 1
                          else None))
                unpartitioned = pk is None or (
                    isinstance(pk, ast.Constant) and pk.value is None)
                if unpartitioned:
                    findings.append(Finding(
                        rule="loop-unpartitioned-scan", path=src.rel,
                        line=node.lineno,
                        message=(f"unpartitioned query_entities scan "
                                 f"in heartbeat-cadence function "
                                 f"{fn.name!r} without a leader "
                                 f"gate; every node pays it every "
                                 f"interval")))
    return findings


@rule("leader-sweep-no-lease", family="loop")
def check_leader_sweep_no_lease(ctx: AnalysisContext
                                ) -> list[Finding]:
    """A sweep-cadence function that performs unpartitioned scans or
    stamps cross-node decisions (``request_preemption``) must hold a
    NAMED LEASE with a fencing epoch — a call whose name carries the
    ``leader_epoch`` / ``sweep_lease`` idiom (state/leases.py) — and
    any ``request_preemption`` it fires must thread the epoch
    through (a ``leader_epoch=`` keyword). A heartbeat-freshness
    election is not a lease: it cannot fence a deposed leader's
    in-flight writes.

    Provenance: the PR 12 gang janitor shipped with "a brief
    double-leader window during failover is harmless because
    clearing is idempotent" — true for the janitor, already false
    for the preempt sweep sharing the same election, whose stamps
    elect victims (two leaders, two victims for one starved task).
    PR 13 deleted that comment by deleting the window: the election
    became a store lease whose holder abdicates on its own clock
    strictly before a successor can acquire, fenced by a monotonic
    term epoch. This rule keeps the next sweep from re-growing the
    window."""
    findings = []
    for src in ctx.python_files:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if not _is_hot(fn):
                continue
            calls = [n for n in ast.walk(fn)
                     if isinstance(n, ast.Call)]
            names_called = {call_name(n) for n in calls}
            names_called.discard(None)
            unpartitioned = False
            for node in calls:
                if call_name(node) != "query_entities":
                    continue
                pk = (keyword_arg(node, "partition_key")
                      or (node.args[1] if len(node.args) > 1
                          else None))
                if pk is None or (isinstance(pk, ast.Constant)
                                  and pk.value is None):
                    unpartitioned = True
            stamps = "request_preemption" in names_called
            if not unpartitioned and not stamps:
                continue
            leased = any(("leader_epoch" in name
                          or "sweep_lease" in name)
                         for name in names_called)
            if not leased:
                findings.append(Finding(
                    rule="leader-sweep-no-lease", path=src.rel,
                    line=fn.lineno,
                    message=(f"sweep {fn.name!r} performs "
                             f"{'unpartitioned scans' if unpartitioned else 'cross-node stamps'} "
                             f"without holding a named lease (no "
                             f"leader_epoch/sweep_lease call) — a "
                             f"heartbeat-freshness election has a "
                             f"double-leader window and no fencing")))
                continue
            for node in calls:
                if call_name(node) == "request_preemption" and \
                        keyword_arg(node, "leader_epoch") is None:
                    findings.append(Finding(
                        rule="leader-sweep-no-lease", path=src.rel,
                        line=node.lineno,
                        message=(f"request_preemption in sweep "
                                 f"{fn.name!r} does not thread the "
                                 f"lease epoch through "
                                 f"(leader_epoch=...) — a deposed "
                                 f"leader's stamp would be "
                                 f"indistinguishable from the "
                                 f"successor's")))
    return findings


@rule("preempt-grace-unbounded", family="loop")
def check_preempt_grace_unbounded(ctx: AnalysisContext
                                  ) -> list[Finding]:
    """A sweep that stamps preemption notices
    (``request_preemption``) must have a reachable ESCALATION path
    in the same function — a call whose name mentions escalate or
    evict. Without one, a victim that ignores its notice squats on
    the slot forever: the notice is a request, and a request with no
    enforcement ladder is an unbounded grace window.

    Provenance: the PR 10 -> PR 12 gap this rule's PR fixes —
    cooperative-only preemption shipped a sweep that stamped notices
    with NO escalation rung, documented only as an honesty paragraph
    in docs/19; the forcible-eviction drill exists because nothing
    structural kept the next sweep from repeating the shape. Scoped
    to sweep/heartbeat-cadence functions: a manual CLI preempt and
    the chaos injectors carry their own follow-through."""
    findings = []
    for src in ctx.python_files:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if not _is_hot(fn):
                continue
            calls = {call_name(node)
                     for node in ast.walk(fn)
                     if isinstance(node, ast.Call)}
            calls.discard(None)
            if "request_preemption" not in calls:
                continue
            if any("escalat" in name or "evict" in name
                   for name in calls):
                continue
            findings.append(Finding(
                rule="preempt-grace-unbounded", path=src.rel,
                line=fn.lineno,
                message=(f"sweep {fn.name!r} stamps preemption "
                         f"notices but has no reachable escalation "
                         f"path (no escalate/evict call) — a victim "
                         f"that ignores its notice is never "
                         f"evicted")))
    return findings


@rule("loop-sleep-in-sweep", family="loop")
def check_sleep_in_sweep(ctx: AnalysisContext) -> list[Finding]:
    """``time.sleep`` inside a heartbeat/sweep function: the sweep
    runs ON the heartbeat thread, so sleeping there delays the
    node's own liveness signal — long enough, and the orphan-reclaim
    path judges the node dead and steals its running tasks.

    Provenance: the TPU_WEDGE_REPORT.md hang class — the progress
    watchdog exists because blocked control loops turn into
    silently-dead nodes. Waiting belongs in the poll loops (which
    sleep poll_interval between EMPTY polls), never in sweep
    bodies; a sweep that needs to wait should record state and
    finish next interval."""
    findings = []
    for src in ctx.python_files:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if not _is_hot(fn):
                continue
            # The loop driver itself (e.g. _heartbeat_loop) paces on
            # stop_event.wait — a plain while-loop wrapper is exempt
            # only for that idiom, so time.sleep still flags.
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        call_name(node) == "sleep" and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "time":
                    findings.append(Finding(
                        rule="loop-sleep-in-sweep", path=src.rel,
                        line=node.lineno,
                        message=(f"time.sleep inside "
                                 f"heartbeat-cadence function "
                                 f"{fn.name!r} stalls the heartbeat "
                                 f"thread; pace on stop_event.wait "
                                 f"or defer to the next interval")))
    return findings

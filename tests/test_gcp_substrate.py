"""GcpTpuSubstrate logic tests against a mocked gcloud: allocation,
worker registration, bootstrap, fatal-error classification,
resize/suspend/delete — the cloud-path logic verified hermetically."""

import json

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore


def make_pool(slices=1):
    return settings_mod.pool_settings({"pool_specification": {
        "id": "gp", "substrate": "tpu_vm",
        "tpu": {"accelerator_type": "v5litepod-16",
                "num_slices": slices}}})


CREDS = settings_mod.credentials_settings({"credentials": {
    "gcp": {"project": "proj", "zone": "us-central1-a"},
    "storage": {"backend": "memory"}}})


class FakeGcloud:
    """Records gcloud invocations and scripts responses."""

    def __init__(self):
        self.calls = []
        self.fail_create_with = None

    def __call__(self, substrate, *args, parse_json=False, zone=None):
        self.calls.append(args)
        self.last_zone = zone
        verb = args[0]
        if verb == "create" and self.fail_create_with:
            raise RuntimeError(self.fail_create_with)
        if verb == "describe" and parse_json:
            return {"state": getattr(self, "describe_state", "READY"),
                    "networkEndpoints": [
                {"ipAddress": f"10.1.0.{i+1}",
                 "accessConfig": {"externalIp": f"34.0.0.{i+1}"}}
                for i in range(4)]}
        return ""


@pytest.fixture()
def substrate(monkeypatch):
    from batch_shipyard_tpu.substrate import gcp_tpu
    monkeypatch.setattr("shutil.which", lambda name: "/usr/bin/gcloud")
    store = MemoryStateStore()
    sub = gcp_tpu.GcpTpuSubstrate(store, CREDS)
    fake = FakeGcloud()
    monkeypatch.setattr(
        sub, "_gcloud",
        lambda *a, **kw: fake(sub, *a, **kw))
    return store, sub, fake


def test_allocate_registers_workers_and_bootstraps(substrate):
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "creating", "spec": {}})
    sub.allocate_pool(pool)
    nodes = pool_mgr.list_nodes(store, "gp")
    assert len(nodes) == 4
    assert {n.internal_ip for n in nodes} == {
        "10.1.0.1", "10.1.0.2", "10.1.0.3", "10.1.0.4"}
    verbs = [c[0] for c in fake.calls]
    assert verbs.count("create") == 1
    assert verbs.count("ssh") == 1  # --worker=all bootstrap
    ssh_call = [c for c in fake.calls if c[0] == "ssh"][0]
    assert "--worker=all" in ssh_call
    command = [a for a in ssh_call if str(a).startswith("--command=")]
    assert "batch_shipyard_tpu.agent" in command[0]


def test_fatal_allocation_error_classified(substrate):
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "creating", "spec": {}})
    fake.fail_create_with = "gcloud failed (1): QUOTA_EXCEEDED for TPU"
    with pytest.raises(RuntimeError):
        sub.allocate_pool(pool)
    entity = store.get_entity(names.TABLE_POOLS, "pools", "gp")
    assert entity["allocation_error_fatal"] is True


def test_transient_allocation_error_not_fatal(substrate):
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "creating", "spec": {}})
    fake.fail_create_with = "gcloud failed (1): deadline exceeded"
    with pytest.raises(RuntimeError):
        sub.allocate_pool(pool)
    entity = store.get_entity(names.TABLE_POOLS, "pools", "gp")
    assert entity["allocation_error_fatal"] is False


def test_resize_and_delete_slices(substrate):
    store, sub, fake = substrate
    pool = make_pool(slices=1)
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "ready", "spec": {}})
    sub.allocate_pool(pool)
    sub.resize_pool(pool, 2)
    assert len(pool_mgr.list_nodes(store, "gp")) == 8
    sub.resize_pool(pool, 1)
    assert len(pool_mgr.list_nodes(store, "gp")) == 4
    delete_calls = [c for c in fake.calls if c[0] == "delete"]
    assert len(delete_calls) == 1
    sub.deallocate_pool("gp")
    assert pool_mgr.list_nodes(store, "gp") == []


def test_suspend_and_start(substrate):
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "ready", "spec": {}})
    sub.allocate_pool(pool)
    sub.suspend_pool(pool)
    assert all(n.state == "suspended"
               for n in pool_mgr.list_nodes(store, "gp"))
    sub.start_pool(pool)
    verbs = [c[0] for c in fake.calls]
    assert "stop" in verbs and "start" in verbs
    # start re-bootstraps agents
    assert verbs.count("ssh") == 2


def test_remote_login_prefers_external_ip(substrate):
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "ready", "spec": {}})
    sub.allocate_pool(pool)
    ip, port = sub.get_remote_login("gp", "gp-s0-w0")
    assert ip == "34.0.0.1" and port == 22


def test_refresh_node_states_marks_preempted(substrate):
    """Spot reclamation: describe reports PREEMPTED -> every node of
    the slice flips to 'preempted', feeding autoscale
    rebalance_preemption_percentage (gcloud_errors.is_preemption_state)."""
    store, sub, fake = substrate
    pool = make_pool()
    store.insert_entity(names.TABLE_POOLS, "pools", "gp",
                        {"state": "creating", "spec": {}})
    sub.allocate_pool(pool)
    sub.refresh_node_states(pool)  # READY: nothing changes
    assert all(n.state != "preempted"
               for n in pool_mgr.list_nodes(store, "gp"))
    fake.describe_state = "PREEMPTED"
    sub.refresh_node_states(pool)
    states = {n.node_id: n.state
              for n in pool_mgr.list_nodes(store, "gp")}
    assert set(states.values()) == {"preempted"}, states

"""Deterministic chaos engineering for the orchestration layer.

The fault-injection capability SURVEY.md 5.3 notes the reference never
had, grown into a first-class subsystem: a seeded, reproducible fault
schedule (plan.ChaosPlan — same seed, same injection sequence),
injectors threaded through the framework's existing seams
(injectors — store op delay/error wrappers, heartbeat blackout, task
SIGKILL mid-run, SIGSTOP wedge, node preemption on the fakepod
substrate), and a scenario runner (drill.run_drill) that drives a real
fakepod pool through the schedule and asserts the self-healing
invariants: every task completes, no orphaned gang rows or queue
messages, and the goodput partition stays exact.

Surfaces: `shipyard chaos plan|drill` (cli), tools/chaos_drill.py
(standalone runner), and a silicon-proof dry-run phase.
"""

from batch_shipyard_tpu.chaos.plan import (  # noqa: F401
    ChaosPlan, Injection, INJECTION_KINDS)
from batch_shipyard_tpu.chaos.injectors import (  # noqa: F401
    ChaosStore)
from batch_shipyard_tpu.chaos.drill import run_drill  # noqa: F401

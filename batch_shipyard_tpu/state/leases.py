"""Lease-based leadership with monotonic fencing epochs.

Every leader-gated loop in the fleet (the gang janitor, the preempt
sweep, the federation's elastic evaluator) used to elect itself by
heartbeat freshness — "lowest-indexed node with a fresh heartbeat" —
which has an unavoidable double-leader window: a leader whose
heartbeats stall (partitioned from the nodes table, or just slow)
keeps sweeping while its successor elects itself, and both fire the
same cross-node decisions until the old leader's next table read.
PR 12 shipped that window with an "idempotent, so harmless" comment
on the janitor; the preempt sweep's stamps are NOT idempotent across
two leaders (two victims can be elected for one starved task), so the
window was a real badput source under exactly the partition shapes
the chaos drills inject.

This module replaces the election with the store primitives that were
already in ``state/base.py`` and implemented by all three backends
but never used: a named lease per leader role, plus a **fencing
epoch**. The epoch is the generation of a per-lease epoch object,
bumped once per leadership *term* (a fresh acquisition). Because
object generations are monotonic in every backend, epochs order
terms totally: a deposed leader's in-flight write stamped with epoch
E can always be distinguished from (and lose to) the successor's
writes stamped E' > E.

Authority rule (the part that closes the window): a holder only acts
while its lease is locally unexpired — renewal happens through the
store, so a leader partitioned from the store CANNOT extend its term;
when ``expires_at`` (minus a safety margin) passes, it abdicates on
its own clock, strictly before the store would grant the lease to a
successor. No overlap, no double leader.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

from batch_shipyard_tpu.state.base import (
    LeaseHandle, LeaseLostError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Leader roles of the node agent's leader-gated sweeps (the epoch
# objects heimdall exports as shipyard_leader_epoch{lease=...}).
ROLE_GANG_JANITOR = "gang-janitor"
ROLE_PREEMPT_SWEEP = "preempt-sweep"
ROLE_FED_ELASTIC = "fed-elastic"
# Server-side task-factory expander (jobs/expansion.py): exactly one
# agent per pool materializes submitted generator specs into task
# rows + queue messages, fenced per chunk like any other sweep.
ROLE_EXPANDER = "task-expander"
AGENT_LEADER_ROLES = (ROLE_GANG_JANITOR, ROLE_PREEMPT_SWEEP,
                      ROLE_EXPANDER)


class LeaderLease:
    """One named leadership lease + its fencing epoch.

    ``epoch()`` is the single gate: it returns the current term's
    epoch while this owner holds the lease (acquiring a free lease,
    renewing a held one when it nears half-life), or None. Callers
    re-check ``fenced(epoch)`` — a pure local-clock check, no store
    round trip — immediately before every write they fence, so a
    verdict cached at the top of a long scan can never authorize a
    stamp after the term ended.

    ``blocked`` is the partition seam (chaos ``leader_partition``):
    while it returns True no lease traffic reaches the store, exactly
    as if the leader were partitioned from it — authority then decays
    on the local clock alone.
    """

    def __init__(self, store: StateStore, key: str, epoch_key: str,
                 owner: str, duration_seconds: float = 20.0,
                 blocked: Optional[Callable[[], bool]] = None,
                 safety_margin: Optional[float] = None) -> None:
        self._store = store
        self.key = key
        self.epoch_key = epoch_key
        self.owner = owner
        self.duration_seconds = duration_seconds
        self._blocked = blocked or (lambda: False)
        # Abdicate this far BEFORE the store-side expiry: covers the
        # TYPICAL write latency of an in-flight fenced stamp plus
        # modest clock skew, so local authority always ends strictly
        # first. It cannot bound a write whose own retries outlive it
        # — non-idempotent sweep writes therefore re-check fenced()
        # AFTER landing and retract their own late stamps (the
        # agent's preempt sweep).
        self._margin = (safety_margin if safety_margin is not None
                        else min(1.0, duration_seconds * 0.2))
        self._handle: Optional[LeaseHandle] = None
        self._epoch: Optional[int] = None
        # Local authority horizon, OUR monotonic clock: stamped at
        # each successful acquire/renew as issue_time + duration -
        # margin. Deliberately independent of the handle's
        # expires_at: backends disagree on its clock (epoch vs
        # monotonic), and deriving authority from when WE issued the
        # call is strictly conservative — the store granted at least
        # that long.
        self._authority_until = 0.0

    # ----------------------------- authority ---------------------------

    def _locally_valid(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return self._handle is not None and self._authority_until > now

    def held_locally(self) -> bool:
        """Current authority by the local clock alone (no store op)."""
        return self._locally_valid()

    def fenced(self, epoch: Optional[int]) -> bool:
        """True iff this owner still holds the SAME term ``epoch``
        right now — the pre-write fencing check. Pure local: a
        partitioned leader fails it as soon as its lease half-life
        margin lapses, never later than the store-side expiry."""
        return (epoch is not None and self._epoch == epoch
                and self._locally_valid())

    def epoch(self, acquire: bool = True) -> Optional[int]:
        """Acquire-or-renew; returns the term's fencing epoch while
        held, None while another owner leads (or the store is
        unreachable and local authority lapsed). ``acquire=False``
        restricts the call to renewal: a lost lease stays lost until
        a caller that is ALLOWED to start a new term asks."""
        now = time.monotonic()
        if self._blocked():
            # Partitioned from the store: renewal is impossible, so
            # authority ends at the local expiry margin — the window
            # in which the old election double-fired.
            if self._locally_valid(now):
                return self._epoch
            self._handle = None
            return None
        if self._handle is not None:
            remaining = self._authority_until - now
            if remaining < self.duration_seconds * 0.5:
                issued = time.monotonic()
                try:
                    self._handle = self._store.renew_lease(
                        self._handle, self.duration_seconds)
                    self._authority_until = (
                        issued + self.duration_seconds
                        - self._margin)
                except LeaseLostError:
                    self._handle = None
                    self._epoch = None
                except Exception:  # noqa: BLE001 - store hiccup
                    # Could not renew; keep the handle — authority
                    # still decays on the local clock, never extends.
                    logger.debug("lease renew failed for %s",
                                 self.key, exc_info=True)
            if self._locally_valid():
                return self._epoch
            self._handle = None
        if self._handle is None:
            if not acquire:
                return None
            issued = time.monotonic()
            try:
                handle = self._store.acquire_lease(
                    self.key, self.duration_seconds, self.owner)
            except Exception:  # noqa: BLE001 - store hiccup = not us
                logger.debug("lease acquire failed for %s", self.key,
                             exc_info=True)
                return None
            if handle is None:
                return None
            # Fresh term: bump the fencing epoch. The epoch object's
            # generation is the epoch — monotonic in every backend —
            # and its body names the owner for observers (heimdall's
            # shipyard_leader_epoch export, the partition drill).
            try:
                epoch = self._store.put_object(
                    self.epoch_key,
                    json.dumps({
                        "owner": self.owner,
                        "lease": self.key,
                        "acquired_at": util.datetime_utcnow_iso(),
                    }).encode("utf-8"))
            except Exception:  # noqa: BLE001 - no epoch, no term
                # A leader that cannot record its fencing epoch must
                # not act: release and retry next tick.
                logger.warning("epoch bump failed for %s; abdicating",
                               self.key, exc_info=True)
                try:
                    self._store.release_lease(handle)
                except Exception:  # noqa: BLE001 - best effort
                    pass
                return None
            self._handle = handle
            self._epoch = epoch
            self._authority_until = (issued + self.duration_seconds
                                     - self._margin)
            logger.info("lease %s acquired by %s (epoch %d)",
                        self.key, self.owner, epoch)
        return self._epoch if self._locally_valid() else None

    def maintain(self) -> None:
        """Renew-only heartbeat tick: keeps a HELD lease alive between
        sweep intervals without ever acquiring (acquisition belongs to
        the gated loop itself, so a node that never sweeps never
        leads). ``acquire=False`` matters: a renew that comes back
        LeaseLostError must NOT roll straight into a fresh term here —
        at heartbeat cadence the incumbent would beat every
        competitor's sweep-cadence acquisition forever."""
        if self._handle is None or self._blocked():
            return
        self.epoch(acquire=False)

    def release(self) -> None:
        """Graceful abdication (agent shutdown): the successor can
        acquire immediately instead of waiting out the expiry."""
        handle, self._handle, self._epoch = self._handle, None, None
        if handle is None:
            return
        try:
            self._store.release_lease(handle)
        except Exception:  # noqa: BLE001 - expiry reclaims anyway
            logger.debug("lease release failed for %s", self.key,
                         exc_info=True)


def read_leader(store: StateStore, epoch_key: str) -> Optional[dict]:
    """Observer-side view of a lease's current term: the epoch
    object's body plus its generation (the epoch). None when no term
    was ever recorded."""
    try:
        meta = store.get_object_meta(epoch_key)
        body = json.loads(store.get_object(epoch_key).decode("utf-8"))
    except Exception:  # noqa: BLE001 - includes NotFoundError
        return None
    body["epoch"] = meta.generation
    return body

"""Service-VM lifecycle verbs (VERDICT r4 next #3): monitor / fed
proxy / slurm control-plane ssh, suspend, start, status over the
injectable gcloud runner. Reference: shipyard.py:2416-2573 (monitor),
:2573+ (fed proxy), :2918+ (slurm ssh), convoy/fleet.py:4721-4878."""

import pytest

from batch_shipyard_tpu.federation import federation as fed_mod
from batch_shipyard_tpu.federation import provision as fed_prov
from batch_shipyard_tpu.monitor import provision as mon_prov
from batch_shipyard_tpu.slurm import provision as slurm_prov
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
from batch_shipyard_tpu.utils import service_vm


class FakeRunner:
    def __init__(self):
        self.calls = []
        self.status = "RUNNING"

    def __call__(self, argv, **_kw):
        self.calls.append(list(argv))
        joined = " ".join(argv)
        if "describe" in joined and "networkIP" in joined:
            return 0, "10.0.0.9\n", ""
        if "describe" in joined and "status" in joined:
            return 0, f"{self.status}\n", ""
        return 0, "", ""

    def verbs(self):
        return [c[2] + ":" + c[3] for c in self.calls]


@pytest.fixture()
def env():
    store = MemoryStateStore()
    runner = FakeRunner()
    vms = GceVmManager("proj", zone="us-central1-a", runner=runner)
    return store, vms, runner


def test_ssh_argv_shape():
    argv = service_vm.ssh_argv("10.0.0.9", username="ops",
                               ssh_private_key="/k",
                               command="uptime")
    assert argv[0] == "ssh"
    assert "-i" in argv and "/k" in argv
    assert "ops@10.0.0.9" in argv
    assert argv[-1] == "uptime"
    assert service_vm.ssh_argv("10.0.0.9")[-1] == "10.0.0.9"


# ------------------------------ monitor ------------------------------

def test_monitor_lifecycle(env):
    store, vms, runner = env
    mon_prov.provision_monitoring_vm(store, "proj", vms=vms)
    status = mon_prov.monitoring_vm_status(store, vms=vms)
    assert status["vm_status"] == "RUNNING"
    assert status["record"]["internal_ip"] == "10.0.0.9"

    mon_prov.suspend_monitoring_vm(store, vms=vms)
    assert "instances:stop" in runner.verbs()
    assert mon_prov.monitoring_vm_status(
        store, vms=vms)["record"]["state"] == "suspended"

    mon_prov.start_monitoring_vm(store, vms=vms)
    assert "instances:start" in runner.verbs()
    assert mon_prov.monitoring_vm_status(
        store, vms=vms)["record"]["state"] == "running"

    argv = mon_prov.monitoring_vm_ssh_argv(store, username="ops")
    assert "ops@10.0.0.9" in argv


def test_monitor_verbs_require_registration(env):
    store, vms, _runner = env
    with pytest.raises(ValueError):
        mon_prov.monitoring_vm_status(store, vms=vms)
    with pytest.raises(ValueError):
        mon_prov.suspend_monitoring_vm(store, vms=vms)
    with pytest.raises(ValueError):
        mon_prov.monitoring_vm_ssh_argv(store)


# ----------------------------- fed proxy -----------------------------

def test_fed_proxy_lifecycle(env):
    store, vms, runner = env
    fed_mod.create_federation(store, "fedx")
    fed_prov.provision_proxy_vm(store, "fedx", "proj", replica=0,
                                vms=vms)
    fed_prov.provision_proxy_vm(store, "fedx", "proj", replica=1,
                                vms=vms)
    status = fed_prov.proxy_vm_status(store, "fedx", vms=vms)
    assert [s["name"] for s in status] == [
        "shipyard-fed-fedx-proxy0", "shipyard-fed-fedx-proxy1"]
    assert all(s["vm_status"] == "RUNNING" for s in status)

    assert fed_prov.suspend_proxy_vms(store, "fedx", vms=vms,
                                      replica=1) == 1
    assert runner.verbs().count("instances:stop") == 1
    assert fed_prov.start_proxy_vms(store, "fedx", vms=vms) == 2
    assert runner.verbs().count("instances:start") == 2

    argv = fed_prov.proxy_vm_ssh_argv(store, "fedx", replica=1)
    assert "10.0.0.9" in argv
    with pytest.raises(ValueError):
        fed_prov.proxy_vm_ssh_argv(store, "fedx", replica=7)
    with pytest.raises(ValueError):
        fed_prov.proxy_vm_status(store, "nope", vms=vms)


# ------------------------------- slurm -------------------------------

def _mk_cluster(store, vms):
    return slurm_prov.create_slurm_cluster(
        store, "clu", "# slurm.conf", "pw", "proj",
        login_count=2, vms=vms)


def test_slurm_cluster_suspend_start(env):
    store, vms, runner = env
    _mk_cluster(store, vms)
    stopped = slurm_prov.suspend_slurm_cluster(store, "clu", vms=vms)
    assert stopped == ["shipyard-slurm-clu-controller",
                       "shipyard-slurm-clu-login0",
                       "shipyard-slurm-clu-login1"]
    assert runner.verbs().count("instances:stop") == 3
    record = slurm_prov.slurm_cluster_status(store, "clu")["cluster"]
    assert record["state"] == "suspended"
    started = slurm_prov.start_slurm_cluster(store, "clu", vms=vms)
    assert len(started) == 3
    assert runner.verbs().count("instances:start") == 3


def test_slurm_ssh_targets(env):
    store, vms, _runner = env
    _mk_cluster(store, vms)
    assert "10.0.0.9" in slurm_prov.slurm_ssh_argv(
        store, "clu", target="controller")
    assert "10.0.0.9" in slurm_prov.slurm_ssh_argv(
        store, "clu", target="login", index=1)
    with pytest.raises(ValueError):
        slurm_prov.slurm_ssh_argv(store, "clu", target="login",
                                  index=5)
    # node target resolves through burst assignment rows.
    from batch_shipyard_tpu.state import names
    store.upsert_entity(names.TABLE_SLURM, "clu$part",
                        "part-0", {"node_id": "n0",
                                   "internal_ip": "10.1.0.3"})
    argv = slurm_prov.slurm_ssh_argv(
        store, "clu", target="node", partition="part",
        host="part-0", command="hostname")
    assert "10.1.0.3" in argv and argv[-1] == "hostname"
    with pytest.raises(ValueError):
        slurm_prov.slurm_ssh_argv(store, "clu", target="node",
                                  partition="part", host="part-9")
    with pytest.raises(ValueError):
        slurm_prov.slurm_ssh_argv(store, "clu", target="bogus")

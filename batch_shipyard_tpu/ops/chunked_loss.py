"""Chunked tied-embedding cross-entropy, with a Pallas fused kernel.

The LM loss is the last big HBM consumer in the training step: naive
``logits = hidden @ E.T`` materializes a [B*T, V] fp32 tensor (4 GB at
B=16, T=2048, V=32k) in the forward and again as its cotangent. The
XLA path here (the scan that models/transformer.lm_loss_chunked has
always used) bounds that to one [chunk, V] slab per step; the Pallas
path goes further and never materializes logits in HBM at all:

- forward kernel: grid (T-chunks, V-chunks), online-softmax running
  (max, sumexp, gold-logit) accumulators in VMEM scratch — one MXU
  matmul per tile, only per-token ``lse``/``gold`` vectors leave the
  kernel (flash attention's trick applied to the vocab softmax);
- backward: dlogits = (softmax - onehot) * dscale is recomputed
  tile-by-tile from the saved ``lse`` in TWO kernels — grad_hidden
  accumulates over V-chunks with grid (T, V), grad_embedding over
  T-chunks with grid (V, T) — so each accumulator lives in VMEM for a
  run of consecutive grid steps and logits are never stored.

Convention matches ops/fused_norm.py: impl 'pallas' | 'xla' |
'interpret' | 'auto' (validation-marker-gated via ops/kernel_select —
the kernel only self-enables after tools/tpu_checks.py proves it on
silicon; ROADMAP.md names this the next transformer-MFU lever).

No reference counterpart (the reference has no ML compute); the fused
pattern follows public chunked-loss kernels (e.g. Liger) re-derived
for Pallas/TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from batch_shipyard_tpu.ops import kernel_select

# Finite -inf stand-in: keeps every intermediate finite (inf - inf is
# nan; exp(-1e30 - m) underflows to exactly 0 for any real m).
_NEG = -1e30


def _pick_v_chunk(d: int) -> int:
    """Vocab tile sized so (E tile + fp32 accumulator) stay well under
    VMEM: ~8 MB combined at the default."""
    if d <= 1024:
        return 512
    if d <= 2048:
        return 256
    return 128


def _fwd_kernel(tgt_ref, h_ref, e_ref, lse_ref, gold_ref,
                m_scr, s_scr, g_scr, *, v_total, v_chunk, n_v):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        s_scr[...] = jnp.zeros(s_scr.shape, jnp.float32)
        g_scr[...] = jnp.zeros(g_scr.shape, jnp.float32)

    h = h_ref[...].astype(jnp.float32)                    # [bt, D]
    e = e_ref[...].astype(jnp.float32)                    # [bv, D]
    logits = jax.lax.dot_general(
        h, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)               # [bt, bv]
    local = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(vi * v_chunk + local < v_total, logits, _NEG)
    m_prev = m_scr[...]                                   # [bt, 1]
    m_new = jnp.maximum(m_prev,
                        jnp.max(logits, axis=1, keepdims=True))
    s_scr[...] = (s_scr[...] * jnp.exp(m_prev - m_new)
                  + jnp.sum(jnp.exp(logits - m_new), axis=1,
                            keepdims=True))
    m_scr[...] = m_new
    tgt_local = tgt_ref[...] - vi * v_chunk               # [bt, 1]
    g_scr[...] += jnp.sum(
        jnp.where(local == tgt_local, logits, 0.0), axis=1,
        keepdims=True)

    @pl.when(vi == n_v - 1)
    def _fin():
        lse_ref[...] = m_scr[...] + jnp.log(s_scr[...])
        gold_ref[...] = g_scr[...]


def _dlogits(h_ref, e_ref, tgt_ref, ds_ref, lse_ref, vi, v_total,
             v_chunk):
    """Recompute one [bt, bv] tile of (softmax - onehot) * dscale."""
    h = h_ref[...].astype(jnp.float32)
    e = e_ref[...].astype(jnp.float32)
    logits = jax.lax.dot_general(
        h, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    local = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    p = jnp.where(vi * v_chunk + local < v_total,
                  jnp.exp(logits - lse_ref[...]), 0.0)
    onehot = (local == tgt_ref[...] - vi * v_chunk).astype(
        jnp.float32)
    return (p - onehot) * ds_ref[...]


def _bwd_h_kernel(tgt_ref, ds_ref, lse_ref, h_ref, e_ref, gh_ref,
                  acc, *, v_total, v_chunk, n_v):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    dl = _dlogits(h_ref, e_ref, tgt_ref, ds_ref, lse_ref, vi,
                  v_total, v_chunk)                       # [bt, bv]
    acc[...] += jax.lax.dot_general(
        dl, e_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [bt, D]

    @pl.when(vi == n_v - 1)
    def _fin():
        gh_ref[...] = acc[...]


def _bwd_e_kernel(tgt_ref, ds_ref, lse_ref, h_ref, e_ref, ge_ref,
                  acc, *, v_total, v_chunk, n_t):
    vi, ti = pl.program_id(0), pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    dl = _dlogits(h_ref, e_ref, tgt_ref, ds_ref, lse_ref, vi,
                  v_total, v_chunk)                       # [bt, bv]
    acc[...] += jax.lax.dot_general(
        dl, h_ref[...].astype(jnp.float32),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [bv, D]

    @pl.when(ti == n_t - 1)
    def _fin():
        ge_ref[...] = acc[...]


def _pad_rows(x, multiple, fill=0):
    n = x.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=fill)


def _fwd_parts(h2, e, tgt2, v_total, bt, bv, interpret):
    """Run the forward kernel on padded inputs; returns (lse, gold)
    as [N_pad, 1] fp32."""
    n_pad, d = h2.shape
    n_t, n_v = n_pad // bt, e.shape[0] // bv
    kern = functools.partial(_fwd_kernel, v_total=v_total,
                             v_chunk=bv, n_v=n_v)
    return pl.pallas_call(
        kern,
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bv, d), lambda ti, vi: (vi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bt, 1), jnp.float32)] * 3,
        interpret=interpret,
    )(tgt2, h2, e)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _xent_pallas(h2, e, tgt, ignore_id, bt, bv, interpret):
    """Mean masked cross-entropy over [N, D] hidden rows (Pallas)."""
    return _xent_pallas_fwd(h2, e, tgt, ignore_id, bt, bv,
                            interpret)[0]


def _xent_pallas_fwd(h2, e, tgt, ignore_id, bt, bv, interpret):
    v_total = e.shape[0]
    n = h2.shape[0]
    hp = _pad_rows(h2, bt)
    tp = _pad_rows(tgt.astype(jnp.int32)[:, None], bt,
                   fill=ignore_id)
    ep = _pad_rows(e, bv)
    lse, gold = _fwd_parts(hp, ep, tp, v_total, bt, bv, interpret)
    mask = (tp != ignore_id).astype(jnp.float32)          # [N_pad, 1]
    count = jnp.maximum(jnp.sum(mask), 1.0)
    loss = jnp.sum((lse - gold) * mask) / count
    return loss, (h2, e, tgt, lse, mask, count)


def _xent_pallas_bwd(ignore_id, bt, bv, interpret, res, g):
    h2, e, tgt, lse, mask, count = res
    v_total, d = e.shape[0], h2.shape[1]
    hp = _pad_rows(h2, bt)
    tp = _pad_rows(tgt.astype(jnp.int32)[:, None], bt,
                   fill=ignore_id)
    ep = _pad_rows(e, bv)
    n_pad = hp.shape[0]
    n_t, n_v = n_pad // bt, ep.shape[0] // bv
    dscale = (g * mask / count).astype(jnp.float32)       # [N_pad, 1]
    gh = pl.pallas_call(
        functools.partial(_bwd_h_kernel, v_total=v_total, v_chunk=bv,
                          n_v=n_v),
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bv, d), lambda ti, vi: (vi, 0)),
        ],
        out_specs=pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        interpret=interpret,
    )(tp, dscale, lse, hp, ep)
    ge = pl.pallas_call(
        functools.partial(_bwd_e_kernel, v_total=v_total, v_chunk=bv,
                          n_t=n_t),
        grid=(n_v, n_t),
        in_specs=[
            pl.BlockSpec((bt, 1), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((bt, d), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((bv, d), lambda vi, ti: (vi, 0)),
        ],
        out_specs=pl.BlockSpec((bv, d), lambda vi, ti: (vi, 0)),
        out_shape=jax.ShapeDtypeStruct((ep.shape[0], d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        interpret=interpret,
    )(tp, dscale, lse, hp, ep)
    n = h2.shape[0]
    return (gh[:n].astype(h2.dtype), ge[:v_total].astype(e.dtype),
            np.zeros(tgt.shape, jax.dtypes.float0))


_xent_pallas.defvjp(_xent_pallas_fwd, _xent_pallas_bwd)


def _xent_xla(h2, e, tgt, ignore_id, chunk):
    """Scan-chunked XLA path (the historical lm_loss_chunked math):
    one rematerialized [chunk, V] fp32 logits slab at a time."""
    import math as _math

    n = h2.shape[0]
    if n % chunk:
        chunk = _math.gcd(n, chunk) or n
    h_chunks = h2.reshape(n // chunk, chunk, -1)
    t_chunks = tgt.reshape(n // chunk, chunk)

    @jax.checkpoint
    def chunk_nll(h_chunk, t_chunk):
        logits = jnp.einsum(
            "cd,vd->cv", h_chunk.astype(jnp.float32),
            e.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, t_chunk[:, None].astype(jnp.int32),
            axis=-1)[..., 0]
        mask = (t_chunk != ignore_id)
        return (jnp.sum((lse - gold) * mask),
                jnp.sum(mask).astype(jnp.float32))

    def step(carry, xs):
        total, cnt = carry
        nll, k = chunk_nll(*xs)
        return (total + nll, cnt + k), None

    (total, cnt), _ = jax.lax.scan(
        step, (jnp.float32(0.0), jnp.float32(0.0)),
        (h_chunks, t_chunks))
    return total / jnp.maximum(cnt, 1.0)


def chunked_softmax_xent(hidden, embedding, targets,
                         ignore_id: int = -1, impl: str = "auto",
                         chunk_size: int = 128,
                         t_chunk: int = 128,
                         v_chunk: int | None = None):
    """Mean cross-entropy of hidden @ embedding.T against targets,
    without materializing [.., V] logits in HBM.

    hidden: [B, T, D] or [N, D]; embedding: [V, D]; targets matches
    hidden's leading shape. impl: 'pallas' | 'interpret' | 'xla' |
    'auto' (Pallas on TPU once silicon-validated — see module doc).
    """
    if hidden.ndim == 3:
        hidden = hidden.reshape(-1, hidden.shape[-1])
        targets = targets.reshape(-1)
    if impl == "auto":
        impl = kernel_select.resolve_auto("chunked_cross_entropy")
    if impl in ("pallas", "interpret"):
        d = hidden.shape[1]
        if d % 128:
            impl = "xla"  # lane-misaligned model dim: not worth it
        else:
            bv = v_chunk or _pick_v_chunk(d)
            return _xent_pallas(hidden, embedding, targets, ignore_id,
                                t_chunk, bv, impl == "interpret")
    if impl != "xla":
        raise ValueError(f"unknown impl {impl!r}")
    return _xent_xla(hidden, embedding, targets, ignore_id,
                     chunk_size)

"""Assemble one trace into Chrome trace-event JSON (Perfetto-loadable).

Inputs are the two logs a submission writes:

  * TABLE_TRACE spans (trace/spans.py) — the causal chain: submit,
    queue wait, claim, backoff, rendezvous, run, program phases,
    serving requests; every span carries trace/span/parent ids.
  * TABLE_GOODPUT intervals (goodput/events.py) — the accounting
    view; events emitted since this PR carry the same trace/span id
    fields, so a trace's waterfall context (image pull, step windows,
    checkpoint phases) rides along without double instrumentation.

Output is the Chrome trace-event JSON array format (the one format
both chrome://tracing and https://ui.perfetto.dev load directly):
complete ("ph": "X") events with microsecond timestamps, one PROCESS
track per node (pid) and one THREAD track per task-instance / serving
request (tid), span/parent ids preserved under ``args`` so the causal
chain survives into the UI's flow queries.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.trace import spans as trace_spans


def trace_rows(store: StateStore, pool_id: str,
               trace_id: str) -> dict[str, list[dict]]:
    """Every row of one trace: {"spans": [...], "goodput": [...]},
    each sorted by start."""
    span_rows = trace_spans.query(store, pool_id, trace_id=trace_id)
    goodput_rows = goodput_events.query(store, pool_id,
                                        trace_id=trace_id)
    return {"spans": span_rows, "goodput": goodput_rows}


def _track(row: dict) -> tuple[str, str]:
    """(pid, tid) for a row: one process track per node, one thread
    track per task instance / serving request."""
    pid = row.get("node_id") or "client"
    attrs = row.get("attrs") or {}
    if row.get("kind", "").startswith("serve_"):
        tid = f"request {attrs.get('request_id', '?')}"
    else:
        tid = row.get("task_id") or row.get("job_id") or "-"
        instance = attrs.get("instance")
        if instance is not None:
            tid = f"{tid} i{instance}"
    return str(pid), str(tid)


def to_chrome_trace(rows: dict[str, list[dict]],
                    trace_id: str) -> dict[str, Any]:
    """Chrome trace-event JSON object for one trace."""
    events: list[dict] = []
    for source, cat in (("spans", "trace"), ("goodput", "goodput")):
        for row in rows.get(source, ()):
            start = float(row.get("start", 0.0))
            end = float(row.get("end", start))
            pid, tid = _track(row)
            event = {
                "name": row.get("kind", "?"),
                "cat": cat,
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, end - start) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {
                    "trace_id": row.get("trace_id"),
                    "span_id": row.get("span_id"),
                    "parent_span_id": row.get("parent_span_id"),
                    "job_id": row.get("job_id"),
                    "task_id": row.get("task_id"),
                    **(row.get("attrs") or {}),
                },
            }
            events.append(event)
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": trace_id,
                      "spans": len(rows.get("spans", ())),
                      "goodput_events": len(rows.get("goodput", ()))},
    }


def export_trace(store: StateStore, pool_id: str,
                 trace_id: str) -> dict[str, Any]:
    """One-call assemble: rows -> Chrome trace JSON object."""
    return to_chrome_trace(trace_rows(store, pool_id, trace_id),
                           trace_id)


def validate_parent_links(chrome_trace: dict[str, Any]) -> list[str]:
    """Every span-sourced event's parent_span_id must resolve to
    another span of the SAME trace (or be absent at the root), and
    every event must carry the trace id. Returns the list of
    problems (empty = consistent) — the e2e acceptance check."""
    problems: list[str] = []
    events = chrome_trace.get("traceEvents", [])
    trace_id = (chrome_trace.get("otherData") or {}).get("trace_id")
    span_ids = {e["args"].get("span_id") for e in events
                if e.get("cat") == "trace"}
    for event in events:
        args = event.get("args", {})
        if args.get("trace_id") != trace_id:
            problems.append(
                f"{event.get('name')}: trace_id "
                f"{args.get('trace_id')!r} != {trace_id!r}")
        if event.get("cat") != "trace":
            continue
        parent = args.get("parent_span_id")
        if parent is not None and parent not in span_ids:
            problems.append(
                f"{event.get('name')}: parent span {parent!r} not in "
                f"this trace")
    return problems


def render_tree(rows: dict[str, list[dict]]) -> str:
    """Terminal waterfall for ``shipyard trace show``: spans indented
    under their parents, goodput intervals listed after, all with
    millisecond offsets from the trace's first event."""
    span_rows = rows.get("spans", [])
    goodput_rows = rows.get("goodput", [])
    if not span_rows and not goodput_rows:
        return "(no spans recorded for this trace)"
    all_rows = span_rows + goodput_rows
    t0 = min(float(r.get("start", 0.0)) for r in all_rows)

    def fmt(row: dict, depth: int) -> str:
        start = float(row.get("start", 0.0))
        end = float(row.get("end", start))
        where = row.get("node_id") or "-"
        task = row.get("task_id") or ""
        return (f"{(start - t0) * 1e3:>10.1f}ms "
                f"{(end - start) * 1e3:>9.1f}ms  "
                f"{'  ' * depth}{row.get('kind')}"
                f"  [{where}{' ' + task if task else ''}]")

    children: dict[Optional[str], list[dict]] = {}
    by_id = {r.get("span_id"): r for r in span_rows}
    for row in span_rows:
        parent = row.get("parent_span_id")
        if parent not in by_id:
            parent = None  # orphan/root: show at top level
        children.setdefault(parent, []).append(row)

    lines = [f"{'offset':>12} {'duration':>10}  span [node task]",
             "-" * 64]

    def walk(parent: Optional[str], depth: int) -> None:
        for row in sorted(children.get(parent, ()),
                          key=lambda r: r.get("start", 0.0)):
            lines.append(fmt(row, depth))
            walk(row.get("span_id"), depth + 1)

    walk(None, 0)
    if goodput_rows:
        lines.append("-" * 64)
        lines.append("goodput intervals on this trace:")
        for row in goodput_rows:
            lines.append(fmt(row, 0))
    return "\n".join(lines)


def write_chrome_trace(chrome_trace: dict[str, Any],
                       path: str) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(chrome_trace, fh, indent=2)
    return path

"""Continuous batching engine: greedy equivalence with the lockstep
generator, slot reuse, early-eos, and per-slot cache isolation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    tokens = jnp.zeros((1, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(7), tokens)["params"]


def reference_greedy(params, prompt, num_tokens):
    run, _model = inf.make_decoder(CFG, params, max_decode_len=64)
    tokens, _cache = run(jnp.asarray([prompt], jnp.int32), num_tokens,
                         jax.random.PRNGKey(0))
    return list(np.asarray(tokens[0, len(prompt):]))


def test_continuous_batching_matches_lockstep(params):
    """5 requests with different prompt lengths through a 2-slot
    engine produce EXACTLY the tokens batch-1 greedy decoding
    produces for each — slots at different depths don't interfere."""
    rng = np.random.RandomState(0)
    requests = [
        serving.Request(f"r{i}", list(rng.randint(0, 97, (3 + i,))),
                        max_new_tokens=4 + (i % 3))
        for i in range(5)
    ]
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    for req in requests:
        engine.submit(req)
    results = {}
    for _ in range(200):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert set(results) == {r.request_id for r in requests}
    for req in requests:
        want = reference_greedy(params, req.prompt, req.max_new_tokens)
        assert results[req.request_id] == want, (
            req.request_id, results[req.request_id], want)


def test_eos_frees_slot_early(params):
    """A request whose first sampled token is its eos finishes in one
    step and its slot is immediately reused."""
    rng = np.random.RandomState(1)
    prompt = list(rng.randint(0, 97, (4,)))
    first = reference_greedy(params, prompt, 1)[0]
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=64)
    engine.submit(serving.Request("eos", prompt, max_new_tokens=10,
                                  eos_id=first))
    other = list(rng.randint(0, 97, (5,)))
    engine.submit(serving.Request("next", other, max_new_tokens=3))
    results = {}
    for _ in range(50):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert results["eos"] == [first]
    assert results["next"] == reference_greedy(params, other, 3)


def test_submit_rejects_overflow(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=16)
    with pytest.raises(ValueError, match="exceeds max_decode_len"):
        engine.submit(serving.Request("big", [1] * 10,
                                      max_new_tokens=10))

"""End-to-end distributed tracing + on-demand profiling (trace/):
context propagation from `jobs add` through claim/backoff/rendezvous
to program spans, Perfetto export with consistent parent links,
mergeable latency histograms behind the serving percentiles, heimdall
bucket export with the node-staleness rule, and the `jobs profile`
store-flag flow."""

import json
import os
import time
import types

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as gp
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.trace import export as trace_export
from batch_shipyard_tpu.trace import profiling as trace_prof
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.trace.histogram import (BUCKET_EDGES_MS,
                                                LatencyHistogram)

GLOBAL = settings_mod.global_settings({})
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------- histograms -------------------------------

def test_histogram_percentiles_monotone_and_clamped():
    hist = LatencyHistogram.of([1.0, 2.0, 4.0, 8.0, 50.0, 400.0])
    p50, p90, p99 = (hist.percentile(p) for p in (50, 90, 99))
    assert p50 <= p90 <= p99
    assert hist.min <= p50 and p99 <= hist.max
    assert hist.count == 6
    assert hist.mean() == pytest.approx(465.0 / 6)
    assert LatencyHistogram().percentile(50) == 0.0


def test_histogram_merge_is_lossless_and_order_free():
    a = LatencyHistogram.of([1, 5, 9, 100])
    b = LatencyHistogram.of([2000.0, 3.0])
    ab = LatencyHistogram.merged([a, b])
    ba = LatencyHistogram.merged([b, a])
    direct = LatencyHistogram.of([1, 5, 9, 100, 2000.0, 3.0])
    assert ab.counts == ba.counts == direct.counts
    assert ab.count == 6 and ab.total == direct.total
    assert ab.min == direct.min and ab.max == direct.max
    for p in (50, 90, 99):
        assert ab.percentile(p) == direct.percentile(p)


def test_histogram_wire_round_trip_and_junk_rejection():
    hist = LatencyHistogram.of([0.1, 77.0, 3e6])
    assert hist.overflow == 1  # 3e6 ms is past the ~35min ladder top
    back = LatencyHistogram.from_dict(hist.to_dict())
    assert back.counts == hist.counts
    assert back.overflow == 1 and back.count == 3
    assert LatencyHistogram.from_dict(None) is None
    assert LatencyHistogram.from_dict({"counts": [1, 2]}) is None
    foreign = hist.to_dict()
    foreign["edges_ms"] = [1.0, 2.0]
    assert LatencyHistogram.from_dict(foreign) is None


def test_histogram_prometheus_bucket_lines_cumulative():
    hist = LatencyHistogram.of([0.2, 0.2, 3.0])
    lines = hist.prometheus_bucket_lines("m", {"pool": "p"})
    assert f'm_bucket{{pool="p",le="{BUCKET_EDGES_MS[0]:g}"}} 2' \
        in lines
    assert 'm_bucket{pool="p",le="+Inf"} 3' in lines
    assert 'm_count{pool="p"} 3' in lines
    # Cumulative counts never decrease.
    counts = [int(line.rsplit(" ", 1)[1]) for line in lines
              if "_bucket" in line]
    assert counts == sorted(counts)


# ------------------------- context + recorders -------------------------

def test_context_child_entity_and_env_round_trips(monkeypatch):
    root = trace_ctx.TraceContext.new()
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    entity = dict(child.entity_columns())
    again = trace_ctx.TraceContext.from_entity(entity)
    assert again == child
    assert trace_ctx.TraceContext.from_entity({"state": "x"}) is None
    for key, value in child.env().items():
        monkeypatch.setenv(key, value)
    from_env = trace_ctx.TraceContext.from_env()
    assert from_env.trace_id == child.trace_id
    assert from_env.span_id == child.span_id
    monkeypatch.delenv(trace_ctx.TRACE_ID_ENV)
    assert trace_ctx.TraceContext.from_env() is None


def test_store_emit_query_and_prune():
    store = MemoryStateStore()
    ctx = trace_ctx.TraceContext.new()
    sid = trace_spans.emit(store, "p1", trace_spans.SPAN_SUBMIT, ctx,
                           job_id="j1", start=10.0, end=11.0,
                           self_span=True)
    assert sid == ctx.span_id
    child = trace_spans.emit(store, "p1", trace_spans.SPAN_CLAIM, ctx,
                             job_id="j1", start=12.0, end=12.0)
    assert child is not None and child != ctx.span_id
    # Unknown kinds and missing contexts are dropped, never raised.
    assert trace_spans.emit(store, "p1", "nope", ctx) is None
    assert trace_spans.emit(store, "p1", trace_spans.SPAN_CLAIM,
                            None) is None
    rows = trace_spans.query(store, "p1", trace_id=ctx.trace_id)
    assert [r["kind"] for r in rows] == ["submit", "claim"]
    assert rows[1]["parent_span_id"] == ctx.span_id
    assert trace_spans.query(store, "p1", trace_id="other") == []
    removed = trace_spans.prune(store, "p1",
                                older_than_seconds=0.0)
    assert removed == 2
    assert trace_spans.query(store, "p1") == []


def test_local_recorder_and_ingest(tmp_path, monkeypatch):
    path = str(tmp_path / "spans.jsonl")
    ctx = trace_ctx.TraceContext.new()
    # No env -> no-op.
    assert trace_spans.record(trace_spans.SPAN_COMPILE, 1.0) is None
    monkeypatch.setenv(trace_ctx.TRACE_FILE_ENV, path)
    for key, value in ctx.env().items():
        monkeypatch.setenv(key, value)
    sid = trace_spans.record(trace_spans.SPAN_COMPILE, 1.0, 2.0,
                             what="warmup")
    assert sid is not None
    with trace_spans.phase(trace_spans.SPAN_CKPT_SNAPSHOT,
                           step=4) as attrs:
        attrs["extra"] = 1
    # Junk lines must be skipped by the ingest, not raised.
    with open(path, "a", encoding="utf-8") as fh:
        fh.write("not json\n")
        fh.write(json.dumps({"kind": "undeclared", "trace_id": "x",
                             "span_id": "y", "start": 1}) + "\n")
        fh.write(json.dumps({"kind": "compile"}) + "\n")
    store = MemoryStateStore()
    count = trace_spans.ingest_local_spans(
        store, "p1", path, job_id="j1", task_id="t1", node_id="n1")
    assert count == 2
    assert not os.path.exists(path)
    rows = trace_spans.query(store, "p1", trace_id=ctx.trace_id)
    assert {r["kind"] for r in rows} == {"compile",
                                         "checkpoint_snapshot"}
    for row in rows:
        assert row["parent_span_id"] == ctx.span_id
        assert row["task_id"] == "t1" and row["node_id"] == "n1"
    snap = next(r for r in rows
                if r["kind"] == "checkpoint_snapshot")
    assert snap["attrs"]["step"] == 4 and snap["attrs"]["extra"] == 1


def test_goodput_record_attaches_trace_ids(tmp_path, monkeypatch):
    ctx = trace_ctx.TraceContext.new()
    gfile = str(tmp_path / "goodput.jsonl")
    monkeypatch.setenv(gp.GOODPUT_FILE_ENV, gfile)
    for key, value in ctx.env().items():
        monkeypatch.setenv(key, value)
    gp.record(gp.PROGRAM_STEP_WINDOW, 1.0, 2.0, step_start=0,
              step_end=4, tokens=32)
    store = MemoryStateStore()
    assert gp.ingest_local_events(store, "p1", gfile, job_id="j1",
                                  task_id="t1") == 1
    events = gp.query(store, "p1", trace_id=ctx.trace_id)
    assert len(events) == 1
    assert events[0]["span_id"] == ctx.span_id
    # Legacy rows (no trace id) don't match a trace filter.
    gp.emit(store, "p1", gp.TASK_QUEUED, job_id="j1", start=1.0,
            end=2.0)
    assert len(gp.query(store, "p1", trace_id=ctx.trace_id)) == 1
    assert len(gp.query(store, "p1")) == 2


# ------------------------------- export --------------------------------

def test_export_chrome_trace_and_parent_validation():
    store = MemoryStateStore()
    root = trace_ctx.TraceContext.new()
    trace_spans.emit(store, "p1", trace_spans.SPAN_SUBMIT, root,
                     job_id="j1", start=10.0, end=10.5,
                     self_span=True)
    task = root.child()
    trace_spans.emit(store, "p1", trace_spans.SPAN_TASK_RUN, task,
                     job_id="j1", task_id="t1", node_id="n1",
                     start=11.0, end=15.0, self_span=True)
    trace_spans.emit(store, "p1", trace_spans.SPAN_QUEUE_WAIT, task,
                     job_id="j1", task_id="t1", node_id="n1",
                     start=10.5, end=11.0)
    gp.emit(store, "p1", gp.PROGRAM_STEP_WINDOW, job_id="j1",
            task_id="t1", node_id="n1", start=12.0, end=14.0,
            attrs={"step_start": 0, "step_end": 8},
            trace_id=root.trace_id, span_id=task.span_id)
    chrome = trace_export.export_trace(store, "p1", root.trace_id)
    events = chrome["traceEvents"]
    assert {e["name"] for e in events} == {
        "submit", "task_run", "queue_wait", "step_window"}
    assert chrome["otherData"]["spans"] == 3
    assert chrome["otherData"]["goodput_events"] == 1
    # Microsecond complete events, sorted by ts, tracked per node.
    assert events == sorted(events, key=lambda e: e["ts"])
    run = next(e for e in events if e["name"] == "task_run")
    assert run["ph"] == "X" and run["pid"] == "n1"
    assert run["dur"] == pytest.approx(4e6)
    assert trace_export.validate_parent_links(chrome) == []
    # A dangling parent is flagged.
    orphan = trace_ctx.TraceContext(root.trace_id, "aaaa", "missing")
    trace_spans.emit(store, "p1", trace_spans.SPAN_CLAIM, orphan,
                     job_id="j1", start=11.0, self_span=True)
    chrome = trace_export.export_trace(store, "p1", root.trace_id)
    assert trace_export.validate_parent_links(chrome)
    tree = trace_export.render_tree(
        trace_export.trace_rows(store, "p1", root.trace_id))
    assert "submit" in tree and "task_run" in tree


# ------------------------------ profiling ------------------------------

def test_step_profiler_capture_flow(tmp_path, monkeypatch):
    calls = []
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    request = str(tmp_path / "req.json")
    profile_dir = str(tmp_path / "prof")
    spans_file = str(tmp_path / "spans.jsonl")
    ctx = trace_ctx.TraceContext.new()
    monkeypatch.setenv(trace_ctx.TRACE_FILE_ENV, spans_file)
    for key, value in ctx.env().items():
        monkeypatch.setenv(key, value)
    profiler = trace_prof.StepProfiler(request_path=request,
                                       profile_dir=profile_dir)
    profiler.tick(0)
    assert not profiler.active and not calls
    trace_prof.write_request(request, steps=2)
    profiler.tick(1)
    assert profiler.active
    assert not os.path.exists(request)  # consumed: one request, one
    profiler.tick(2)                    # capture
    assert profiler.active
    profiler.tick(3)
    assert not profiler.active
    assert calls == [("start", profile_dir), ("stop",)]
    with open(spans_file, encoding="utf-8") as fh:
        spans = [json.loads(line) for line in fh]
    assert spans[-1]["kind"] == trace_spans.SPAN_PROFILE
    assert spans[-1]["attrs"]["step_start"] == 1
    assert spans[-1]["attrs"]["step_end"] == 3
    # close() stops a capture cut short by loop exit.
    trace_prof.write_request(request, steps=100)
    profiler.tick(4)
    assert profiler.active
    profiler.close()
    assert not profiler.active and calls[-1] == ("stop",)


def test_step_profiler_broken_profiler_disarms(tmp_path,
                                               monkeypatch):
    import jax

    def boom(_):
        raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    request = str(tmp_path / "req.json")
    trace_prof.write_request(request, steps=3)
    profiler = trace_prof.StepProfiler(
        request_path=request, profile_dir=str(tmp_path / "p"))
    profiler.tick(0)  # must not raise into the step loop
    assert not profiler.active
    trace_prof.write_request(request, steps=3)
    profiler.tick(1)  # broken: stays disarmed, doesn't retry forever
    assert not profiler.active


# ---------------- serving percentiles + heimdall buckets ---------------

def test_serving_percentiles_merge_and_heimdall_buckets(tmp_path,
                                                        monkeypatch):
    """The serving acceptance run: loadgen against two replicas
    produces monotone p50 <= p90 <= p99 TTFT/TPOT from MERGED
    per-replica histograms (loadgen report, server stats, router
    aggregation agree on the rule), the fronts record per-request
    trace spans, and heimdall turns those spans into Prometheus
    ``_bucket`` lines — excluding spans from stale nodes."""
    import jax
    import jax.numpy as jnp

    from batch_shipyard_tpu.models import loadgen, serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.router import ServingRouter
    from batch_shipyard_tpu.models.server import ServingFrontEnd
    from batch_shipyard_tpu.monitor import heimdall

    ctx = trace_ctx.TraceContext.new()
    spans_file = str(tmp_path / "serve_spans.jsonl")
    monkeypatch.setenv(trace_ctx.TRACE_FILE_ENV, spans_file)
    for key, value in ctx.env().items():
        monkeypatch.setenv(key, value)

    cfg = tfm.TransformerConfig(
        vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    params = tfm.TransformerLM(cfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    fronts = [ServingFrontEnd(
        serving.ContinuousBatcher(cfg, params, num_slots=2,
                                  max_decode_len=64),
        port=0).start() for _ in range(2)]
    router = None
    try:
        report = loadgen.run_load(
            [f.url for f in fronts], num_requests=12, rate_hz=50.0,
            prompt_len=(2, 8), max_new_tokens=(2, 6), vocab_size=97,
            seed=11)
        assert report["completed"] == 12 and report["failed"] == 0
        for metric in ("ttft_ms", "tpot_ms"):
            pcts = report[metric]
            assert pcts["p50"] <= pcts["p90"] <= pcts["p99"]
        assert report["ttft_hist"]["count"] == 12

        # Server-side per-replica histograms merge losslessly to the
        # same fleet totals.
        merged = LatencyHistogram.merged(
            LatencyHistogram.from_dict(f.stats()["ttft_hist"])
            for f in fronts)
        assert merged.count == 12
        assert merged.percentile(50) <= merged.percentile(90) <= \
            merged.percentile(99)
        # Each front exposes native _bucket exposition.
        front_text = "\n".join(fronts[0].prometheus_metrics())
        assert "shipyard_serving_ttft_ms_bucket{" in front_text
        assert "shipyard_serving_tpot_ms_count" in front_text

        # Router aggregation: merged-histogram percentiles fleet-wide.
        router = ServingRouter([f.url for f in fronts],
                               health_interval=0.2).start()
        deadline = time.monotonic() + 15
        stats = {}
        while time.monotonic() < deadline:
            stats = router.stats()
            if stats.get("ttft_ms"):
                break
            time.sleep(0.1)
        assert stats.get("ttft_hist", {}).get("count") == 12
        assert stats["ttft_ms"][50] <= stats["ttft_ms"][90] <= \
            stats["ttft_ms"][99]
        router_text = "\n".join(router.prometheus_metrics())
        assert "shipyard_router_ttft_ms_bucket{" in router_text
    finally:
        if router is not None:
            router.shutdown()
        for front in fronts:
            front.shutdown()

    # The fronts recorded per-request span chains through the
    # process-local recorder; heimdall rebuilds the pool's latency
    # histogram from them, honoring the node-staleness rule.
    store = MemoryStateStore()
    store.insert_entity(names.TABLE_POOLS, "pools", "spool",
                        {"state": "ready"})
    now = time.time()
    store.insert_entity(names.TABLE_NODES, "spool", "node-a",
                        {"state": "idle", "heartbeat_at": now})
    store.insert_entity(names.TABLE_NODES, "spool", "node-b",
                        {"state": "idle",
                         "heartbeat_at": now - 9999.0})
    count = trace_spans.ingest_local_spans(
        store, "spool", spans_file, job_id="jserve",
        task_id="t0", node_id="node-a")
    assert count >= 12 * 4  # request + queued + prefill + decode
    # A crashed replica's spans (stale node-b) must not export.
    trace_spans.emit(
        store, "spool", trace_spans.SPAN_SERVE_REQUEST, ctx,
        job_id="jserve", task_id="t1", node_id="node-b",
        start=now - 10, end=now,
        attrs={"request_id": "ghost", "ttft_ms": 1e6,
               "tpot_ms": 1e6, "num_tokens": 1})
    gp.emit(store, "spool", gp.PROGRAM_STEP_WINDOW, job_id="jserve",
            node_id="node-a", start=now - 8, end=now - 4,
            attrs={"step_start": 0, "step_end": 8})
    gp.emit(store, "spool", gp.PROGRAM_STEP_WINDOW, job_id="jserve",
            node_id="node-b", start=now - 8, end=now - 4,
            attrs={"step_start": 0, "step_end": 8})
    lines = heimdall.build_goodput_metrics(store)
    text = "\n".join(lines)
    assert 'shipyard_serving_ttft_ms_bucket{le=' not in text  # labeled
    assert 'shipyard_serving_ttft_ms_count{pool="spool"} 12' in text
    assert 'shipyard_serving_tpot_ms_bucket{' in text
    # node-a's last-step gauge exports; stale node-b's does not.
    assert 'node_last_step_seconds{node="node-a",pool="spool"} ' \
        '0.500000' in text
    assert 'node="node-b"' not in text


# ---------------------------- fakepod e2e ------------------------------

@pytest.fixture()
def fakepod_env():
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    conf = {"pool_specification": {
        "id": "pool1", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16", "num_slices": 1},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    # Keep the injected retry's backoff short so the e2e stays fast.
    substrate.agent_kwargs = {"retry_backoff_base": 0.4}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    yield store, substrate, pool
    substrate.stop_all()


def _ctx_for(store, pool):
    """Minimal fleet.Context stand-in for actions that only read
    .store and .pool."""
    return types.SimpleNamespace(store=store, pool=pool)


def test_e2e_gang_submission_exports_consistent_trace(fakepod_env,
                                                      tmp_path):
    """The acceptance run: one `jobs add` gang submission with an
    injected retry yields ONE trace whose Chrome export covers
    submit -> claim -> backoff -> rendezvous -> train steps with
    consistent trace/parent ids, while the goodput partition on the
    same run stays exact."""
    store, substrate, pool = fakepod_env
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    # Attempt 0: every instance drops a marker and fails (the
    # injected chaos); the requeued attempt finds the markers and
    # records a train step window through the goodput recorder (trace
    # ids attach from the exported env).
    command = (
        'M="$MARKER_DIR/done.$SHIPYARD_TASK_INSTANCE"; '
        'if [ ! -e "$M" ]; then touch "$M"; exit 1; fi; '
        "python3 -c \"import time; "
        "from batch_shipyard_tpu.goodput import events; "
        "t = time.time(); "
        "events.record('step_window', t, t + 0.05, step_start=0, "
        "step_end=4, tokens=32)\"")
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "jtrace",
            "tasks": [{
                "command": command,
                "max_task_retries": 2,
                "environment_variables": {
                    "MARKER_DIR": marker_dir,
                    "PYTHONPATH": REPO_ROOT,
                },
                "multi_instance": {
                    "num_instances": 2,
                    "jax_distributed": {"enabled": False},
                },
            }],
        }]}))
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jtrace",
                                    timeout=60)
    assert tasks[0]["state"] == "completed"
    assert tasks[0]["retries"] == 1
    trace_id = tasks[0][trace_ctx.COL_TRACE_ID]
    assert trace_id
    # Job row carries the same trace.
    job = jobs_mgr.get_job(store, "pool1", "jtrace")
    assert job[trace_ctx.COL_TRACE_ID] == trace_id

    want = {trace_spans.SPAN_SUBMIT, trace_spans.SPAN_CLAIM,
            trace_spans.SPAN_QUEUE_WAIT, trace_spans.SPAN_REQUEUE,
            trace_spans.SPAN_BACKOFF_WAIT,
            trace_spans.SPAN_RENDEZVOUS, trace_spans.SPAN_TASK_RUN}
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        kinds = {r["kind"] for r in trace_spans.query(
            store, "pool1", trace_id=trace_id)}
        if want <= kinds:
            break
        time.sleep(0.1)
    assert want <= kinds, f"missing spans: {want - kinds}"

    chrome = trace_export.export_trace(store, "pool1", trace_id)
    assert trace_export.validate_parent_links(chrome) == []
    by_name = {}
    for event in chrome["traceEvents"]:
        by_name.setdefault(event["name"], []).append(event)
    # The train steps joined the trace through the goodput recorder.
    assert "step_window" in by_name
    assert by_name["step_window"][0]["args"]["trace_id"] == trace_id
    # Both instances rendezvoused (per-instance spans).
    assert {e["args"].get("instance")
            for e in by_name["gang_rendezvous"]} >= {0, 1}
    # Span rows all share the submission's trace id, and the task
    # chain parents under the submit root.
    submit = by_name["submit"][0]["args"]
    assert submit["parent_span_id"] is None
    run = by_name["task_run"][0]["args"]
    assert run["parent_span_id"] == submit["span_id"]

    # Goodput on the SAME run: trace-tagged events exist, the trace
    # filter scopes them, and the partition stays exact.
    events = gp.query(store, "pool1", trace_id=trace_id)
    kinds = {e["kind"] for e in events}
    assert {gp.TASK_QUEUED, gp.TASK_RUNNING, gp.TASK_BACKOFF,
            gp.PROGRAM_STEP_WINDOW} <= kinds
    assert gp.query(store, "pool1", trace_id="nosuchtrace") == []
    report = accounting.job_report(store, "pool1", "jtrace")
    total = report["productive_seconds"] + sum(
        report["badput_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)
    scoped = accounting.job_report(store, "pool1", "jtrace",
                                   trace_id=trace_id)
    assert scoped["trace_id"] == trace_id
    assert scoped["events"] == len(events)
    scoped_total = scoped["productive_seconds"] + sum(
        scoped["badput_seconds"].values())
    assert scoped_total == pytest.approx(scoped["wall_seconds"],
                                         rel=0.01)

    # `jobs tasks list` surfaces the trace id.
    from batch_shipyard_tpu import fleet
    import io
    import sys as sys_mod
    out = io.StringIO()
    stdout, sys_mod.stdout = sys_mod.stdout, out
    try:
        fleet.action_jobs_tasks_list(_ctx_for(store, pool), "jtrace",
                                     raw=True)
    finally:
        sys_mod.stdout = stdout
    listed = json.loads(out.getvalue())
    assert listed["tasks"][0]["trace_id"] == trace_id


def test_cli_trace_surface(tmp_path):
    """CLI smoke: jobs add -> tasks list exposes the trace id ->
    trace show/export/prune and goodput --trace run end-to-end
    through click."""
    import yaml
    from click.testing import CliRunner

    from batch_shipyard_tpu.cli.main import cli
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"docker_images": []}},
        "pool": {"pool_specification": {
            "id": "tpool", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-8"},
            "max_wait_time_seconds": 30}},
        "jobs": {"job_specifications": [{
            "id": "tjob",
            "tasks": [{"command": "echo traced"}]}]},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    configdir = str(tmp_path)
    runner = CliRunner()
    for argv in (["pool", "add"], ["jobs", "add"],
                 ["jobs", "wait", "--job-id", "tjob",
                  "--timeout", "30"]):
        result = runner.invoke(cli, ["--configdir", configdir] + argv,
                               catch_exceptions=False)
        assert result.exit_code == 0, result.output
    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "jobs", "tasks",
              "list", "tjob"], catch_exceptions=False)
    trace_id = json.loads(result.output)["tasks"][0]["trace_id"]
    result = runner.invoke(
        cli, ["--configdir", configdir, "trace", "show", trace_id],
        catch_exceptions=False)
    assert result.exit_code == 0 and "submit" in result.output
    out_path = str(tmp_path / "chrome.json")
    result = runner.invoke(
        cli, ["--configdir", configdir, "trace", "export", trace_id,
              "-o", out_path], catch_exceptions=False)
    assert result.exit_code == 0
    with open(out_path, encoding="utf-8") as fh:
        chrome = json.load(fh)
    assert chrome["otherData"]["trace_id"] == trace_id
    assert {e["name"] for e in chrome["traceEvents"]} >= {
        "submit", "task_run"}
    assert trace_export.validate_parent_links(chrome) == []
    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "goodput", "job",
              "tjob", "--trace", trace_id], catch_exceptions=False)
    assert result.exit_code == 0
    report = json.loads(result.output)
    assert report["trace_id"] == trace_id and report["events"] > 0
    result = runner.invoke(
        cli, ["--configdir", configdir, "trace", "prune",
              "--older-than-hours", "0"], catch_exceptions=False)
    assert result.exit_code == 0 and "pruned" in result.output


def test_e2e_profile_request_flow(fakepod_env):
    """`jobs profile` store flag -> agent forwards at launch -> task
    writes a capture into the profile dir -> agent uploads it and
    stamps profile_artifact, surfaced by `jobs tasks list`."""
    store, substrate, pool = fakepod_env
    from batch_shipyard_tpu import fleet
    # Stamp the flag BEFORE submitting: launch-time delivery.
    store.insert_entity(names.TABLE_JOBS, "pool1", "jprof-pre",
                        {"state": "active", "spec": {}})
    fleet.action_jobs_profile(_ctx_for(store, pool), "jprof-pre",
                              steps=3)
    job = jobs_mgr.get_job(store, "pool1", "jprof-pre")
    assert job[trace_prof.COL_PROFILE_REQUEST]["steps"] == 3
    store.delete_entity(names.TABLE_JOBS, "pool1", "jprof-pre")

    # The request may arrive at launch (fast path) or via the
    # heartbeat forwarding loop once the agent's short-TTL job cache
    # refreshes — poll briefly like a real step loop would.
    command = (
        'for _ in $(seq 1 150); do '
        'test -f "$SHIPYARD_PROFILE_REQUEST_FILE" && break; '
        'sleep 0.1; done; '
        'test -f "$SHIPYARD_PROFILE_REQUEST_FILE" && '
        'mkdir -p "$SHIPYARD_PROFILE_DIR" && '
        'echo capture > "$SHIPYARD_PROFILE_DIR/trace.pb"')
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "jprof", "tasks": [{"command": command}]}]}))
    fleet.action_jobs_profile(_ctx_for(store, pool), "jprof",
                              steps=2)
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jprof",
                                    timeout=30)
    assert tasks[0]["state"] == "completed", tasks[0]
    deadline = time.monotonic() + 10
    task = tasks[0]
    while time.monotonic() < deadline:
        task = jobs_mgr.get_task(store, "pool1", "jprof",
                                 task["_rk"])
        if task.get(trace_prof.COL_PROFILE_ARTIFACT):
            break
        time.sleep(0.1)
    artifact = task[trace_prof.COL_PROFILE_ARTIFACT]
    assert artifact.endswith("/profile")
    data = store.get_object(artifact + "/trace.pb")
    assert data.strip() == b"capture"
    # Surfaced next to the diagnostics column.
    import io
    import sys as sys_mod
    out = io.StringIO()
    stdout, sys_mod.stdout = sys_mod.stdout, out
    try:
        fleet.action_jobs_tasks_list(_ctx_for(store, pool), "jprof",
                                     raw=True)
    finally:
        sys_mod.stdout = stdout
    listed = json.loads(out.getvalue())
    assert listed["tasks"][0]["profile_artifact"] == artifact

"""Data movement tests: object ingress/egress, sharded transfer
planning, task input/output staging (reference data.py behaviors)."""

import os

import pytest

from batch_shipyard_tpu.data import movement
from batch_shipyard_tpu.state.memory import MemoryStateStore


@pytest.fixture()
def tree(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("aaa")
    (src / "b.dat").write_text("b" * 100)
    (src / "sub" / "c.txt").write_text("ccc")
    return src


def test_ingress_egress_roundtrip(tree, tmp_path):
    store = MemoryStateStore()
    count = movement.ingress_to_storage(store, str(tree), "ing/data")
    assert count == 3
    assert store.get_object("ing/data/a.txt") == b"aaa"
    assert store.get_object("ing/data/sub/c.txt") == b"ccc"
    out = tmp_path / "out"
    assert movement.egress_from_storage(store, "ing/data", str(out)) == 3
    assert (out / "sub" / "c.txt").read_text() == "ccc"


def test_ingress_include_exclude(tree):
    store = MemoryStateStore()
    count = movement.ingress_to_storage(
        store, str(tree), "f", include=["*.txt", "sub/*"],
        exclude=["sub/c.txt"])
    assert count == 1
    assert store.list_objects("f/") == ["f/a.txt"]


def test_multinode_transfer_plan_balances_by_size():
    files = [(f"f{i}", size) for i, size in
             enumerate([100, 90, 50, 40, 30, 10])]
    nodes = [("n0", "10.0.0.1", 22), ("n1", "10.0.0.2", 22)]
    plan = movement.plan_multinode_transfer(files, nodes, "/data")
    assert len(plan) == 2
    loads = {c.node_id: c.total_bytes for c in plan}
    # greedy largest-first: n0 gets 100+40+30=170? check balance < 2x
    assert abs(loads["n0"] - loads["n1"]) <= 100
    all_files = [f for c in plan for f in c.files]
    assert sorted(all_files) == sorted(f for f, _ in files)
    # scp command shape
    assert plan[0].argv[0] == "scp"
    assert plan[0].argv[-1].endswith(":/data")


def test_multinode_transfer_rsync():
    plan = movement.plan_multinode_transfer(
        [("x", 1)], [("n0", "1.2.3.4", 2222)], "/dst", method="rsync",
        ssh_username="me", ssh_private_key="/k")
    argv = plan[0].argv
    assert argv[0] == "rsync"
    assert "me@1.2.3.4:/dst" in argv
    assert any("-p 2222" in a for a in argv)


def test_stage_task_inputs_single_and_prefix(tmp_path):
    store = MemoryStateStore()
    store.put_object("in/one.txt", b"1")
    store.put_object("ds/x/a", b"a")
    store.put_object("ds/x/b/c", b"bc")
    task_dir = tmp_path / "task"
    movement.stage_task_inputs(store, [
        {"kind": "statestore", "key": "in/one.txt",
         "file_path": "one.txt"},
        {"kind": "statestore", "key": "ds/x", "file_path": "data"},
    ], str(task_dir))
    assert (task_dir / "one.txt").read_bytes() == b"1"
    assert (task_dir / "data" / "a").read_bytes() == b"a"
    assert (task_dir / "data" / "b" / "c").read_bytes() == b"bc"


def test_collect_task_outputs(tmp_path):
    store = MemoryStateStore()
    task_dir = tmp_path / "task"
    (task_dir / "results").mkdir(parents=True)
    (task_dir / "results" / "out.npy").write_text("x")
    (task_dir / "stdout.txt").write_text("log")
    count = movement.collect_task_outputs(
        store, [{"include": "results/*"}], str(task_dir),
        "p", "j", "t")
    assert count == 1
    keys = store.list_objects("taskdata/p/j/t/outputs")
    assert keys == ["taskdata/p/j/t/outputs/results/out.npy"]


def test_task_input_data_e2e():
    """Full path: object in store -> input_data -> task reads it."""
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    store.put_object("inputs/greeting.txt", b"hello-from-storage")
    substrate = FakePodSubstrate(store)
    conf = {"pool_specification": {
        "id": "dp", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    pool = S.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings({}), conf)
        jobs = S.job_settings_list({"job_specifications": [{
            "id": "dj",
            "tasks": [{
                "command": "cat greeting.txt",
                "input_data": [{"kind": "statestore",
                                "key": "inputs/greeting.txt",
                                "file_path": "greeting.txt"}],
                "output_data": [{"include": "*.out"}],
            }],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "dp", "dj", timeout=30)
        assert tasks[0]["state"] == "completed"
        out = jobs_mgr.get_task_output(store, "dp", "dj", "task-00000")
        assert out.strip() == b"hello-from-storage"
    finally:
        substrate.stop_all()


# ---------------- round-4: splits + streaming ingress -----------------

def test_split_plan_offsets_match_reference_semantics():
    """One 1000-byte file, split at 300 bytes over 2 nodes: pieces
    carry contiguous [begin, end) offsets (reference data.py:635-661),
    piece 0 keeps the final name, later pieces get the zero-padded
    _shipyard- suffix, and load balances across nodes."""
    files = [("/src/big.bin", 1000)]
    nodes = [("n0", "10.0.0.1", 22), ("n1", "10.0.0.2", 22)]
    plan = movement.plan_multinode_transfer(
        files, nodes, "/data", split_bytes=300)
    pieces = sorted((p for c in plan for p in c.pieces),
                    key=lambda p: p.begin)
    assert [(p.begin, p.end) for p in pieces] == [
        (0, 300), (300, 600), (600, 900), (900, 1000)]
    assert pieces[0].dst == "/data/big.bin"
    assert pieces[1].dst == "/data/big.bin._shipyard-1"
    assert pieces[3].dst == "/data/big.bin._shipyard-3"
    assert all(p.final_dst == "/data/big.bin" for p in pieces)
    # Both nodes participate: the single file rides every NIC.
    assert len(plan) == 2
    loads = sorted(c.total_bytes for c in plan)
    assert loads == [400, 600] or loads == [500, 500]
    # Small files below the threshold stay whole.
    plan2 = movement.plan_multinode_transfer(
        [("/src/small", 100)], nodes, "/data", split_bytes=300)
    assert all(not c.pieces for c in plan2)


def test_split_transfer_executes_and_reassembles(tmp_path, monkeypatch):
    """Drive run_transfers over a split plan with a PATH-shimmed ssh
    that writes `cat > dst` stdin locally: pieces land with correct
    bytes and the join reassembles the original file."""
    import stat
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    sandbox = tmp_path / "node-fs"
    sandbox.mkdir()
    ssh = bin_dir / "ssh"
    ssh.write_text(f"""#!/usr/bin/env python3
import os, subprocess, sys
# last arg is the remote command; everything before is ssh plumbing
cmd = sys.argv[-1]
os.chdir({str(sandbox)!r})
cmd = cmd.replace('"/', '"{sandbox}/')
sys.exit(subprocess.call(["/bin/bash", "-c", cmd]))
""")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH",
                       f"{bin_dir}{os.pathsep}" + os.environ["PATH"])
    src = tmp_path / "big.bin"
    payload = bytes(range(256)) * 40  # 10240 bytes, distinct content
    src.write_bytes(payload)
    plan = movement.plan_multinode_transfer(
        [(str(src), len(payload))],
        [("n0", "127.0.0.1", 22), ("n1", "127.0.0.2", 22)],
        "/data", split_bytes=3000)
    (sandbox / "data").mkdir()
    rcs = movement.run_transfers(plan, max_parallel=2)
    assert all(rc == 0 for rc in rcs)
    assert (sandbox / "data" / "big.bin").read_bytes() == payload
    # pieces were cleaned up by the join
    leftovers = [p for p in (sandbox / "data").iterdir()
                 if "_shipyard-" in p.name]
    assert leftovers == []


def test_streaming_ingress_bounded_memory(tmp_path):
    """Ingress a 512 MB file through the localfs store in a
    subprocess and assert peak RSS stays far below the file size
    (the whole-file-in-memory OOM the reference's blobxfer streaming
    avoids, convoy/data.py:62)."""
    import subprocess
    import sys
    big = tmp_path / "big.dat"
    size = 512 * 1024 * 1024
    with open(big, "wb") as fh:  # sparse file: fast to create
        fh.seek(size - 1)
        fh.write(b"\0")
    probe = f"""
import sys, tracemalloc
sys.path.insert(0, {repr(str(os.getcwd()))})
from batch_shipyard_tpu.data import movement
from batch_shipyard_tpu.state.localfs import LocalFSStateStore
store = LocalFSStateStore({repr(str(tmp_path / 'store'))})
# tracemalloc (not ru_maxrss): measures Python-level allocations,
# immune to allocator/THP noise under full-suite load — the claim
# under test is "the file is never materialized in memory".
tracemalloc.start()
n = movement.ingress_to_storage(store, {repr(str(big))}, "ingest")
assert n == 1
meta = store.get_object_meta("ingest/big.dat")
assert meta.size == {size}, meta.size
# egress back out, still streaming
n = movement.egress_from_storage(store, "ingest",
                                 {repr(str(tmp_path / 'out'))})
assert n == 1
peak_mb = tracemalloc.get_traced_memory()[1] / (1024 * 1024)
print(f"RSS_MB={{peak_mb:.0f}}")
assert peak_mb < 128, f"peak alloc {{peak_mb:.0f}} MB - not streaming"
"""
    out = subprocess.run([sys.executable, "-c", probe],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "RSS_MB=" in out.stdout

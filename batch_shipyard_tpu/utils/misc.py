"""Misc helpers: TensorBoard tunnel, image mirroring.

Reference analog: convoy/misc.py — tunnel_tensorboard(:62: pick the
logdir from a running task, start a TensorBoard container on its node,
local ssh port-forward) and image mirroring (:250).
"""

from __future__ import annotations

import os
from typing import Optional

from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.utils import crypto, util

logger = util.get_logger(__name__)

TENSORBOARD_PORT = 6006


def plan_tensorboard_tunnel(
        store: StateStore, substrate, pool_id: str, job_id: str,
        task_id: str, logdir: Optional[str] = None,
        local_port: int = 16006,
        ssh_username: str = "shipyard",
        ssh_private_key: Optional[str] = None,
        output_dir: str = ".") -> dict:
    """Resolve the task's node, synthesize the remote TensorBoard
    launch command and the local tunnel script (tunnel_tensorboard
    analog). Returns the plan; execution is the caller's choice."""
    task = jobs_mgr.get_task(store, pool_id, job_id, task_id)
    node_id = task.get("node_id")
    if not node_id:
        raise ValueError(f"task {task_id} has no assigned node yet")
    login = substrate.get_remote_login(pool_id, node_id)
    if login is None:
        raise ValueError(f"no remote login for node {node_id}")
    ip, port = login
    node = store.get_entity(names.TABLE_NODES, pool_id, node_id)
    if logdir is None:
        # Default: the task's working directory on the node.
        logdir = f"/var/shipyard/tasks/{job_id}/{task_id}"
    remote_cmd = (
        f"python3 -m tensorboard.main --logdir {logdir} "
        f"--port {TENSORBOARD_PORT} --bind_all")
    script_path = crypto.ssh_tunnel_script(
        ip, port, local_port, TENSORBOARD_PORT, ssh_username,
        ssh_private_key,
        os.path.join(output_dir, f"tunnel-tb-{task_id}.sh"))
    return {
        "node_id": node_id, "node_ip": ip, "ssh_port": port,
        "hostname": node.get("hostname"),
        "remote_command": remote_cmd,
        "tunnel_script": script_path,
        "local_url": f"http://localhost:{local_port}",
    }


def mirror_images_plan(images: list[str],
                       dest_registry: str) -> list[list[str]]:
    """Command plan to mirror images into a private registry
    (misc.py:250 analog)."""
    plan: list[list[str]] = []
    for image in images:
        target = f"{dest_registry}/{image.split('/')[-1]}"
        plan.append(["docker", "pull", image])
        plan.append(["docker", "tag", image, target])
        plan.append(["docker", "push", target])
    return plan

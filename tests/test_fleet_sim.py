"""Fleet-simulator + shared-policy tests (ISSUE 17).

Three contracts pinned here:

1. **Determinism** — same (seed, trace, policy) => byte-identical
   report and equal fingerprint; this is what makes a sim policy
   delta attributable to the policy instead of to noise, and what
   the `sim-wall-clock` analyzer rule protects statically.
2. **Policy behavior** — the tier-1 smoke (<=200 virtual nodes,
   seconds of wall time) shows warm-cache claim affinity beating the
   baseline bundle on the steady scenario, priced by the production
   goodput engine with an exact partition; a slow-marked sweep runs
   the >=2,000-node shape the bench artifact commits.
3. **No forked copies** — the sim prices the SAME pure functions
   (sched/policy.py) the live agent claim path, preemption sweep,
   and pool autoscaler import; the decision code is defined exactly
   once.
"""

import ast
import json
import pathlib

import pytest

from batch_shipyard_tpu.agent import progress
from batch_shipyard_tpu.sched import policy as sched_policy
from batch_shipyard_tpu.sim import scenarios as sim_scenarios
from batch_shipyard_tpu.sim import simulator as sim_mod

PACKAGE = pathlib.Path(sched_policy.__file__).resolve().parent.parent
REPO_ROOT = PACKAGE.parent


# --------------------------- policy units ---------------------------

def test_claim_score_prices_cold_health_and_backoff():
    """A warm healthy node is a perfect claim (0.0); every debit —
    cold compile, poor health, recent failures — adds seconds, so
    scores compose by addition and order totally."""
    knobs = sched_policy.PolicyKnobs()
    assert sched_policy.claim_score(warm=True) == 0.0
    cold = sched_policy.claim_score(warm=False)
    assert cold == knobs.warm_cache_bonus_seconds
    # No identity advertised -> no cold-compile leg to price.
    assert sched_policy.claim_score(warm=False,
                                    has_identity=False) == 0.0
    sick = sched_policy.claim_score(warm=True, health=0.5)
    assert sick == pytest.approx(0.5 * knobs.health_debit_seconds)
    flaky = sched_policy.claim_score(warm=True, recent_failures=2)
    assert flaky == 2 * knobs.backoff_debit_seconds
    # The failure debit caps at 4: backoff cannot blacklist forever.
    assert sched_policy.claim_score(warm=True, recent_failures=99) \
        == sched_policy.claim_score(warm=True, recent_failures=4)


def test_should_defer_claim_window_never_starves():
    """A costly claim on a YOUNG task defers back to the queue; past
    the affinity window the claim always proceeds — affinity trades
    queueing seconds for compile seconds, never starvation."""
    knobs = sched_policy.PolicyKnobs()
    costly = sched_policy.claim_score(warm=False, knobs=knobs)
    assert sched_policy.should_defer_claim(costly, 0.0, knobs)
    assert not sched_policy.should_defer_claim(
        costly, knobs.claim_affinity_wait_seconds, knobs)
    assert not sched_policy.should_defer_claim(0.0, 0.0, knobs)


def test_victim_cost_orders_committed_cold_below_warm_uncommitted():
    """The drill shape: a task that just committed and holds no warm
    identity is the cheap victim; a warm task far past its last
    commit is expensive. Gang width scales the whole cost (every
    instance replays)."""
    cheap = sched_policy.victim_cost(
        warm=False, steps_since_commit=0, step_seconds=0.5)
    costly = sched_policy.victim_cost(
        warm=True, steps_since_commit=60, step_seconds=0.5)
    assert cheap == 0.0 < costly
    assert sched_policy.victim_cost(
        warm=True, steps_since_commit=60, step_seconds=0.5,
        gang_size=4) == pytest.approx(4 * costly)


def test_victim_cost_from_row_prices_synced_hints():
    """The live-row pricer reads the sched_hints column the agent
    mirrors from the workload's hints file; a hint-less task prices
    at 0.0 and falls back to the (priority, cost, task_id)
    tie-break."""
    from batch_shipyard_tpu.state import names
    assert sched_policy.victim_cost_from_row({}) == 0.0
    row = {names.TASK_COL_SCHED_HINTS: {
        "step": 80, "ckpt_step": 20, "step_seconds": 0.5,
        "cache_identity": "digest"}}
    expected = sched_policy.victim_cost(
        warm=True, steps_since_commit=60, step_seconds=0.5)
    assert sched_policy.victim_cost_from_row(row) == \
        pytest.approx(expected)
    # Sort key: priority dominates, then cost, then task id — never
    # scan order.
    keys = sorted([
        sched_policy.victim_sort_key(10, 0.0, "a"),
        sched_policy.victim_sort_key(0, 99.0, "z"),
        sched_policy.victim_sort_key(0, 0.0, "b"),
        sched_policy.victim_sort_key(0, 0.0, "a"),
    ])
    assert keys == [(0, 0.0, "a"), (0, 0.0, "b"), (0, 99.0, "z"),
                    (10, 0.0, "a")]


def test_record_sched_hints_round_trip(tmp_path, monkeypatch):
    """Workload-side publication: partial updates merge (a
    checkpointer knows ckpt_step, the step loop knows step), the
    write is atomic tmp+rename, and no env var means no-op."""
    hints_file = tmp_path / "hints.json"
    monkeypatch.setenv(progress.SCHED_HINTS_FILE_ENV,
                       str(hints_file))
    progress.record_sched_hints(step=5, step_seconds=0.5,
                                cache_identity="digest")
    progress.record_sched_hints(ckpt_step=5)
    progress.record_sched_hints(step=9)
    assert progress.read_sched_hints(str(hints_file)) == {
        "step": 9, "ckpt_step": 5, "step_seconds": 0.5,
        "cache_identity": "digest"}
    monkeypatch.delenv(progress.SCHED_HINTS_FILE_ENV)
    progress.record_sched_hints(step=99)  # hints disabled: no-op
    assert progress.read_sched_hints(str(hints_file))["step"] == 9


def test_autoscale_target_marginal_trade_and_damped_drain():
    knobs = sched_policy.PolicyKnobs()
    # Deep backlog: scale up past the busy floor, and the reason
    # names the trade.
    target, why = sched_policy.autoscale_target(
        pending_tasks=500, active_tasks=10, current_nodes=10,
        slots_per_node=1, knobs=knobs)
    assert target > 10 and "provisioning" in why
    # Empty queue: drain TOWARD the busy floor at most 10% per call
    # (a cliff would churn provisioning on the next burst).
    target, why = sched_policy.autoscale_target(
        pending_tasks=0, active_tasks=10, current_nodes=100,
        slots_per_node=1, knobs=knobs)
    assert target == 90 and "drain" in why
    # Never below the busy floor.
    target, _ = sched_policy.autoscale_target(
        pending_tasks=0, active_tasks=50, current_nodes=52,
        slots_per_node=1, knobs=knobs)
    assert target >= 50
    # A trickle inside tolerance is not worth provisioning for.
    target, why = sched_policy.autoscale_target(
        pending_tasks=1, active_tasks=4, current_nodes=4,
        slots_per_node=1, knobs=knobs)
    assert target == 4 and "tolerance" in why


# --------------------------- determinism ----------------------------

def test_sim_report_byte_identical_for_same_seed_trace_policy():
    """THE determinism contract: two fresh simulator instances over
    the same (seed, trace, policy) produce byte-identical canonical
    JSON (and therefore equal fingerprints); a different seed moves
    the fingerprint. This holds under `-p no:randomly` and any test
    ordering because the sim owns its RNG and its clock."""
    kwargs = sim_scenarios.build("steady", seed=3, nodes=50,
                                 tasks=400)
    first = sim_mod.run_sim(policy="combined", **kwargs)
    again = sim_mod.run_sim(
        policy="combined",
        **sim_scenarios.build("steady", seed=3, nodes=50, tasks=400))
    assert json.dumps(first, sort_keys=True) == \
        json.dumps(again, sort_keys=True)
    assert first["fingerprint"] == again["fingerprint"]
    other = sim_mod.run_sim(
        policy="combined",
        **sim_scenarios.build("steady", seed=4, nodes=50, tasks=400))
    assert other["fingerprint"] != first["fingerprint"]
    assert first["partition_exact"], first["partition_error"]


# ------------------------- tier-1 smoke -----------------------------

def test_sim_smoke_affinity_beats_baseline_on_steady():
    """The tier-1 policy proof at smoke scale (100 virtual nodes,
    1,000 tasks — seconds of wall time): warm-cache claim affinity
    converts compile badput into a higher goodput ratio than the
    baseline bundle on the same seed, and both partitions are exact
    (productive + badput + overlapped == node-seconds wall)."""
    reports = {
        name: sim_mod.run_sim(
            policy=name,
            **sim_scenarios.build("steady", seed=0, nodes=100,
                                  tasks=1000))
        for name in ("baseline", "affinity")}
    for rep in reports.values():
        assert rep["partition_exact"], rep["partition_error"]
        assert rep["scheduler"]["tasks_completed"] == 1000
    compared = sim_mod.compare(reports)
    delta = compared["affinity"]["delta_vs_baseline"]
    assert delta["goodput_ratio_delta"] > 0.0
    # The win is specifically a compile-badput conversion.
    assert delta["badput_seconds_delta"].get("compile", 0.0) < 0.0
    assert reports["affinity"]["fingerprint"] != \
        reports["baseline"]["fingerprint"]


def test_sim_chaos_preemption_wave_stays_partition_exact():
    """The chaos inventory as scenario schedules: a preemption wave
    (seeded provider kills mid-run) exercises replay + rescheduling
    in virtual time, completes every task, and the goodput partition
    stays exact through the churn."""
    rep = sim_mod.run_sim(
        policy="baseline",
        **sim_scenarios.build("preemption_wave", seed=1, nodes=60,
                              tasks=400))
    assert rep["scheduler"]["preemptions"] > 0
    assert rep["scheduler"]["tasks_completed"] == 400
    assert rep["partition_exact"], rep["partition_error"]
    assert rep["goodput"]["badput_seconds"].get(
        "preemption_recovery", 0.0) > 0.0


# ----------------------- no forked copies ---------------------------

def test_policy_functions_defined_only_in_sched_policy():
    """The decision functions exist exactly once, in
    sched/policy.py — the sim prices the same code the live paths
    run, so a sim delta is a statement about production behavior."""
    owned = {"claim_score", "should_defer_claim", "victim_cost",
             "victim_cost_from_row", "victim_sort_key",
             "autoscale_target"}
    definers: dict = {name: [] for name in owned}
    for path in sorted(PACKAGE.rglob("*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name in owned:
                definers[node.name].append(
                    str(path.relative_to(PACKAGE.parent)))
    for name, sites in definers.items():
        assert sites == ["batch_shipyard_tpu/sched/policy.py"], (
            f"{name} must be defined exactly once in "
            f"sched/policy.py, found {sites}")


def test_live_paths_import_the_shared_policy_module():
    """Claim path + preemption sweep (agent/node_agent.py), pool
    autoscaler (pool/autoscale.py), and the simulator all import
    sched.policy — no consumer carries a private copy."""
    for rel in ("agent/node_agent.py", "pool/autoscale.py",
                "sim/simulator.py"):
        src = (PACKAGE / rel).read_text(encoding="utf-8")
        assert "batch_shipyard_tpu.sched import policy" in src, (
            f"{rel} does not import the shared policy module")
    agent_src = (PACKAGE / "agent" / "node_agent.py").read_text(
        encoding="utf-8")
    for call in ("claim_score", "should_defer_claim",
                 "victim_cost_from_row", "victim_sort_key"):
        assert f"sched_policy.{call}(" in agent_src, (
            f"node_agent.py does not call sched_policy.{call}")
    autoscale_src = (PACKAGE / "pool" / "autoscale.py").read_text(
        encoding="utf-8")
    assert "sched_policy.autoscale_target(" in autoscale_src


# --------------------------- CLI surface ----------------------------

def test_sim_actions_run_scenarios_compare():
    """The `shipyard sim` actions: scenarios inventories every
    scenario + policy bundle; run returns a fingerprinted report;
    compare always includes the baseline control and prices deltas
    against it."""
    from batch_shipyard_tpu import fleet
    inventory = fleet.action_sim_scenarios(None, raw=True)
    assert set(inventory["scenarios"]) == \
        set(sim_scenarios.SCENARIOS)
    assert set(inventory["policies"]) == set(sched_policy.POLICIES)
    report = fleet.action_sim_run(None, scenario="steady",
                                  policy="baseline", seed=0,
                                  nodes=20, tasks=60, raw=True)
    assert report["fingerprint"] and report["partition_exact"]
    summary = fleet.action_sim_compare(None, scenario="steady",
                                       policies=("affinity",),
                                       seed=0, nodes=20, tasks=60,
                                       raw=True)
    assert set(summary["runs"]) == {"baseline", "affinity"}
    assert "goodput_ratio_delta" in \
        summary["policies"]["affinity"]


# ------------------------- fleet scale (slow) -----------------------

@pytest.mark.slow
def test_sim_fleet_scale_sweep_2000_nodes():
    """The bench shape at tier-2: >=2,000 virtual nodes, every task
    completed, partition exact, and still byte-deterministic (the
    fingerprint is stable across two fresh runs)."""
    build = lambda: sim_scenarios.build(  # noqa: E731
        "steady", seed=1, nodes=2000, tasks=20_000)
    first = sim_mod.run_sim(policy="combined", **build())
    assert first["nodes"] >= 2000
    assert first["scheduler"]["tasks_completed"] == 20_000
    assert first["partition_exact"], first["partition_error"]
    again = sim_mod.run_sim(policy="combined", **build())
    assert again["fingerprint"] == first["fingerprint"]

"""ChaosPlan: a deterministic, seeded fault schedule.

Reproducibility is the whole point: a drill that only fails one run in
twenty is useless for regression-testing recovery code. A plan is a
pure function of (seed, shape parameters) — same seed, same injection
sequence, byte for byte — so a failing drill replays exactly, and two
operators comparing notes can name a fault scenario by its seed.

The schedule is substrate-agnostic: targets are logical node INDICES
(resolved against the live pool at apply time by chaos/drill.py) and
times are offsets from drill start.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
from typing import Optional

# Injection vocabulary. Each kind maps onto one existing framework
# seam (see chaos/injectors.py):
#   store_delay        — latency on every state-store op for a window
#   store_error        — a burst of injected store-op failures
#   heartbeat_blackout — node keeps working, heartbeats suppressed
#   task_kill          — SIGKILL a running task's process group
#   task_wedge         — SIGSTOP a running task: alive, zero progress
#                        (the TPU-wedge shape; only the progress
#                        watchdog can catch it)
#   node_preempt       — hard-kill a node agent (no offline write),
#                        revive after a delay
#   node_preempt_notice — ADVANCE-NOTICE preemption (the cloud
#                        spot/preemptible shape): stamp a cooperative
#                        preempt request on the node's running task,
#                        then crash the node after the notice window
#                        if the task is still running — a
#                        preempt-aware workload drains and exits
#                        first; an oblivious one eats the hard kill
#   victim_ignore_notice — stamp a cooperative preempt request on a
#                        running task and do NOTHING else: the
#                        victim (an --ignore-notice probe) squats
#                        past the grace window, and the sweep's
#                        eviction escalation — not the injector —
#                        must hard-kill it (the forcible-eviction
#                        drill's shape)
#   host_loss_resize   — crash `count` nodes of the pool with no
#                        revive: permanent capacity loss mid-gang,
#                        forcing the elastic resize + multi-host
#                        reshard-on-restore path
#   pool_capacity_loss — crash EVERY node of the pool: the gang can
#                        never re-form here, and only cross-pool
#                        migration (federation) can finish the job
#   store_outage       — the state store goes DOWN for a sustained
#                        window (every faulted op fails, not a
#                        per-op burst): only the resilient-store
#                        ride-through (critical retry + advisory
#                        WAL, state/resilient.py) survives it
#   leader_partition   — stall ONLY the current sweep leader's
#                        heartbeats and lease renewals while its
#                        sweep loop keeps running: the exact shape
#                        the old heartbeat-freshness election
#                        double-fired under; the lease must make it
#                        abdicate on its own clock
#   agent_restart      — the agent PROCESS dies (in-flight
#                        completion paths cut off, no offline write)
#                        while its task subprocesses keep running,
#                        then restarts on the same work_dir: the
#                        crash-restart adoption shape
#   replica_kill       — SIGKILL-shaped death of a serving replica
#                        mid-decode (socket torn down, no drain, no
#                        final stream line): the router must resume
#                        every live stream on a sibling with
#                        exactly-once token delivery
#   replica_drain_notice — a preempt/evict notice lands on a serving
#                        replica: it must flip to draining (healthz
#                        503+marker, no new admissions, in-flight
#                        decodes run to the grace deadline) while the
#                        router routes around it and resumes any
#                        drain-abandoned decode elsewhere
#   router_restart     — the serving ROUTER process dies mid-stream
#                        and a fresh one takes over the same fleet:
#                        clients re-submit with resume_tokens
#                        (cancel-then-resume), and the replicas'
#                        duplicate gates keep delivery exactly-once
INJECTION_KINDS = ("store_delay", "store_error", "heartbeat_blackout",
                   "task_kill", "task_wedge", "node_preempt",
                   "node_preempt_notice", "victim_ignore_notice",
                   "host_loss_resize", "pool_capacity_loss",
                   "store_outage", "leader_partition",
                   "agent_restart", "replica_kill",
                   "replica_drain_notice", "router_restart")

# Kinds a GENERIC drill's recovery invariants can absorb — the
# default schedule. The fleet-elasticity kinds are excluded: they
# exist to drive their dedicated drills (eviction / host-resize /
# migration, chaos/drill.py), and e.g. pool_capacity_loss in a
# single-pool generic drill is unrecoverable by construction (only
# cross-pool migration finishes the job). The control-plane kinds
# (store_outage / leader_partition / agent_restart) are likewise
# dedicated-drill shapes: a sustained outage without the resilient
# wrapper armed is unrecoverable by construction, and the other two
# need their drills' orchestrated setups to make the invariants
# non-vacuous. The serving kinds (replica_kill /
# replica_drain_notice / router_restart) target a serving fleet —
# replicas + router, not a batch pool — so they only make sense
# inside the serving drills (chaos/serving_drill.py), which stand
# that fleet up around the plan.
DEFAULT_DRILL_KINDS = ("store_delay", "store_error",
                       "heartbeat_blackout", "task_kill",
                       "task_wedge", "node_preempt",
                       "node_preempt_notice")


@dataclasses.dataclass(frozen=True)
class Injection:
    at: float           # seconds from drill start
    kind: str           # one of INJECTION_KINDS
    node_index: int     # logical target node (resolved at apply time)
    params: tuple       # sorted (key, value) pairs — hashable/frozen

    def param(self, key: str, default=None):
        return dict(self.params).get(key, default)

    def to_dict(self) -> dict:
        return {"at": self.at, "kind": self.kind,
                "node_index": self.node_index,
                "params": dict(self.params)}


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    seed: int
    duration: float
    num_nodes: int
    injections: tuple[Injection, ...]

    @classmethod
    def generate(cls, seed: int, duration: float = 4.0,
                 num_nodes: int = 4,
                 kinds: Optional[tuple[str, ...]] = None,
                 injections_per_kind: int = 1) -> "ChaosPlan":
        """Deterministic schedule: for each requested kind, draw
        ``injections_per_kind`` (time, target, params) tuples from a
        seed-keyed RNG. Faults land in the middle 70% of the drill
        window so the pool has formed before the first one and has
        runway to recover after the last. Default kinds are the
        generic-drill-recoverable set (DEFAULT_DRILL_KINDS); the
        fleet-elasticity kinds must be requested explicitly."""
        kinds = tuple(kinds or DEFAULT_DRILL_KINDS)
        unknown = [k for k in kinds if k not in INJECTION_KINDS]
        if unknown:
            raise ValueError(f"unknown injection kinds {unknown}")
        rng = random.Random(seed)
        out: list[Injection] = []
        lo, hi = 0.1 * duration, 0.8 * duration
        for kind in kinds:
            for _ in range(max(1, injections_per_kind)):
                at = round(rng.uniform(lo, hi), 3)
                node_index = rng.randrange(max(1, num_nodes))
                params: dict = {}
                if kind == "store_delay":
                    params = {"delay": round(rng.uniform(0.01, 0.05),
                                             3),
                              "window": round(rng.uniform(0.5, 1.5),
                                              3)}
                elif kind == "store_error":
                    params = {"ops": rng.randrange(2, 6)}
                elif kind == "heartbeat_blackout":
                    params = {"window": round(rng.uniform(1.0, 2.5),
                                              3)}
                elif kind == "node_preempt":
                    params = {"revive_after":
                              round(rng.uniform(0.3, 1.0), 3)}
                elif kind == "node_preempt_notice":
                    params = {"notice":
                              round(rng.uniform(0.4, 1.2), 3),
                              "revive_after":
                              round(rng.uniform(0.3, 1.0), 3)}
                elif kind == "host_loss_resize":
                    params = {"count": 1}
                elif kind == "store_outage":
                    params = {"window": round(rng.uniform(1.0, 2.5),
                                              3)}
                elif kind == "leader_partition":
                    params = {"window": round(rng.uniform(2.0, 4.0),
                                              3)}
                elif kind == "agent_restart":
                    params = {"revive_after":
                              round(rng.uniform(0.3, 0.8), 3)}
                elif kind == "replica_drain_notice":
                    params = {"grace":
                              round(rng.uniform(0.5, 2.0), 3)}
                elif kind == "router_restart":
                    params = {"downtime":
                              round(rng.uniform(0.1, 0.4), 3)}
                out.append(Injection(
                    at=at, kind=kind, node_index=node_index,
                    params=tuple(sorted(params.items()))))
        out.sort(key=lambda i: (i.at, i.kind, i.node_index))
        return cls(seed=seed, duration=duration, num_nodes=num_nodes,
                   injections=tuple(out))

    @classmethod
    def preemption_wave(cls, seed: int, at: float, num_nodes: int,
                        fraction: float = 0.3,
                        revive_after: float = 60.0,
                        stagger: float = 5.0) -> "ChaosPlan":
        """A provider preemption WAVE: ``fraction`` of the fleet is
        reclaimed without notice inside a short window starting at
        ``at`` (targets and offsets drawn from a seed-keyed RNG —
        distinct nodes, staggered like a real zone reclaim, and a
        pure function of the arguments). The fleet-simulator's
        chaos-schedule scenario; also drivable against a live pool
        via the generic injector path."""
        rng = random.Random(seed)
        count = max(1, int(num_nodes * fraction))
        targets = rng.sample(range(max(1, num_nodes)),
                             min(count, max(1, num_nodes)))
        out = [Injection(
            at=round(at + rng.uniform(0.0, stagger), 3),
            kind="node_preempt", node_index=idx,
            params=tuple(sorted(
                {"revive_after": revive_after}.items())))
            for idx in targets]
        out.sort(key=lambda i: (i.at, i.kind, i.node_index))
        return cls(seed=seed, duration=at + revive_after + stagger,
                   num_nodes=num_nodes, injections=tuple(out))

    def to_dict(self) -> dict:
        return {"seed": self.seed, "duration": self.duration,
                "num_nodes": self.num_nodes,
                "fingerprint": self.fingerprint(),
                "injections": [i.to_dict() for i in self.injections]}

    @classmethod
    def from_dict(cls, data: dict) -> "ChaosPlan":
        return cls(
            seed=int(data["seed"]), duration=float(data["duration"]),
            num_nodes=int(data["num_nodes"]),
            injections=tuple(
                Injection(at=float(i["at"]), kind=i["kind"],
                          node_index=int(i["node_index"]),
                          params=tuple(sorted(
                              (i.get("params") or {}).items())))
                for i in data["injections"]))

    def fingerprint(self) -> str:
        """Stable digest of the injection sequence — two plans with
        the same fingerprint inject identically (the determinism
        acceptance check)."""
        payload = json.dumps(
            [i.to_dict() for i in self.injections], sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

"""Goodput accounting subsystem: event log, decomposition engine,
emit sites end-to-end on the fakepod substrate, and the atomic
checkpoint commit that keeps the lost-step rework number honest.

All synthetic timelines use small absolute epochs — the engine is
pure over event dicts, so nothing here sleeps for accounting."""

import json
import os
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as gp
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore

GLOBAL = settings_mod.global_settings({})


def _ev(kind, start, end, job_id="j1", task_id="t1", node_id="n1",
        **attrs):
    return {"kind": kind, "start": float(start), "end": float(end),
            "job_id": job_id, "task_id": task_id, "node_id": node_id,
            "attrs": attrs}


# ----------------------------- event log -------------------------------

def test_emit_span_query_roundtrip():
    store = MemoryStateStore()
    gp.emit(store, "p1", gp.TASK_QUEUED, job_id="j1", task_id="t1",
            start=10.0, end=12.5, attrs={"retries": 0})
    with gp.span(store, "p1", gp.TASK_IMAGE_PULL, job_id="j1",
                 task_id="t1") as attrs:
        attrs["image"] = "img"
    events = gp.query(store, "p1")
    assert [e["kind"] for e in events] == [gp.TASK_QUEUED,
                                           gp.TASK_IMAGE_PULL]
    assert events[0]["end"] - events[0]["start"] == pytest.approx(2.5)
    assert events[1]["attrs"]["image"] == "img"
    assert gp.query(store, "p1", job_id="nope") == []


def test_unknown_kind_dropped_and_emit_never_raises():
    store = MemoryStateStore()
    gp.emit(store, "p1", "not_a_kind", start=1.0)
    assert gp.query(store, "p1") == []

    class Broken:
        def insert_entity(self, *a, **k):
            raise RuntimeError("store down")

    gp.emit(Broken(), "p1", gp.TASK_QUEUED, start=1.0)  # no raise


def test_local_recorder_and_ingest(tmp_path, monkeypatch):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv(gp.GOODPUT_FILE_ENV, path)
    with gp.phase(gp.PROGRAM_COMPILE, what="warmup"):
        pass
    gp.record(gp.PROGRAM_STEP_WINDOW, 5.0, 9.0, step_start=0,
              step_end=4, tokens=1024)
    lines = open(path, encoding="utf-8").read().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[1])["attrs"]["tokens"] == 1024
    store = MemoryStateStore()
    count = gp.ingest_local_events(store, "p1", path, job_id="j1",
                                   task_id="t1", node_id="n1")
    assert count == 2
    assert not os.path.exists(path)  # consumed: retries can't double
    events = gp.query(store, "p1", job_id="j1")
    assert {e["kind"] for e in events} == {gp.PROGRAM_COMPILE,
                                           gp.PROGRAM_STEP_WINDOW}


def test_ingest_skips_malformed_task_written_lines(tmp_path):
    """The JSONL is task-controlled: junk must neither raise into the
    agent's task flow nor poison downstream accounting."""
    path = tmp_path / "events.jsonl"
    path.write_text("\n".join([
        "not json at all",
        json.dumps({"kind": "step_window", "start": "abc"}),
        json.dumps({"kind": "step_window", "start": 1.0, "end": 2.0,
                    "attrs": ["not", "a", "dict"]}),
        json.dumps({"kind": "step_window", "start": 3.0, "end": 4.0,
                    "attrs": {"step_start": "a", "step_end": "b"}}),
        json.dumps({"kind": "step_window", "start": 5.0, "end": 6.0,
                    "attrs": {"step_start": 0, "step_end": 5}}),
    ]) + "\n")
    store = MemoryStateStore()
    count = gp.ingest_local_events(store, "p1", str(path),
                                   job_id="j1")
    assert count == 3  # the two unparseable-start lines dropped
    # Junk attrs degrade gracefully in the accounting too.
    report = accounting.job_report(store, "p1", "j1")
    assert report["steps"] == 5
    total = report["productive_seconds"] + sum(
        report["badput_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)


def test_gang_identical_step_ranges_counted_once():
    """8 SPMD instances record the same step range: steps/tokens
    count one unit of progress, not 8."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 50.0, node_id=f"n{i}",
            step_start=0, step_end=50, tokens=500)
        for i in range(8)
    ]
    report = accounting.decompose(events)
    assert report["steps"] == 50
    assert report["tokens"] == 500


def test_preemption_downtime_span_priced_as_provisioning():
    """autoscale's preempted->recovered span carries the outage; the
    zero-duration observation markers only bump the counter."""
    events = [
        _ev(gp.NODE_PREEMPTED, 10.0, 10.0, preempted_nodes=2),
        _ev(gp.NODE_PREEMPTED, 10.0, 70.0, recovered=True, nodes=2),
        _ev(gp.NODE_IDLE, 70.0, 100.0, node_id="n1"),
    ]
    report = accounting.decompose(events)
    assert report["preemptions"] == 1
    assert report["badput_seconds"]["provisioning"] == pytest.approx(
        60.0)
    assert report["badput_seconds"]["idle"] == pytest.approx(30.0)


def test_autoscale_preemption_bookkeeping_emits_outage_span():
    from batch_shipyard_tpu.pool import autoscale as as_mod
    store = MemoryStateStore()
    store.upsert_entity("pools", "pools", "p1", {"state": "ready"})
    entity = store.get_entity("pools", "pools", "p1")
    as_mod._record_preemptions(store, entity, "p1", 2)
    markers = [e for e in gp.query(store, "p1")
               if e["kind"] == gp.NODE_PREEMPTED]
    assert len(markers) == 1 and markers[0]["end"] == \
        markers[0]["start"]
    # Same count again: no duplicate emission.
    entity = store.get_entity("pools", "pools", "p1")
    as_mod._record_preemptions(store, entity, "p1", 2)
    assert len([e for e in gp.query(store, "p1")
                if e["kind"] == gp.NODE_PREEMPTED]) == 1
    # Recovery closes the outage with a downtime SPAN.
    entity = store.get_entity("pools", "pools", "p1")
    as_mod._record_preemptions(store, entity, "p1", 0)
    spans = [e for e in gp.query(store, "p1")
             if e["kind"] == gp.NODE_PREEMPTED
             and e["end"] > e["start"]]
    assert len(spans) == 1
    assert spans[0]["attrs"]["recovered"] is True


def test_local_recorder_noop_without_env(monkeypatch, tmp_path):
    monkeypatch.delenv(gp.GOODPUT_FILE_ENV, raising=False)
    gp.record(gp.PROGRAM_COMPILE, 1.0, 2.0)  # must not raise
    assert list(tmp_path.iterdir()) == []


# --------------------------- accounting core ---------------------------

def test_decompose_categories_partition_wall():
    events = [
        _ev(gp.TASK_QUEUED, 0.0, 10.0),
        _ev(gp.TASK_RUNNING, 10.0, 100.0),
        _ev(gp.TASK_IMAGE_PULL, 10.0, 14.0),
        _ev(gp.PROGRAM_COMPILE, 14.0, 24.0),
        _ev(gp.PROGRAM_STEP_WINDOW, 24.0, 84.0, step_start=0,
            step_end=60, tokens=6000),
        _ev(gp.PROGRAM_CHECKPOINT_SAVE, 84.0, 90.0, step=60),
    ]
    report = accounting.decompose(events)
    assert report["wall_seconds"] == pytest.approx(100.0)
    total = report["productive_seconds"] + sum(
        report["badput_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)
    assert report["badput_seconds"]["queueing"] == pytest.approx(10.0)
    assert report["badput_seconds"]["image_pull"] == pytest.approx(4.0)
    assert report["badput_seconds"]["compile"] == pytest.approx(10.0)
    assert report["badput_seconds"]["checkpoint"] == pytest.approx(6.0)
    assert report["productive_seconds"] == pytest.approx(60.0)
    # [90, 100] is the running container with no program phase.
    assert report["badput_seconds"]["unaccounted"] == pytest.approx(
        10.0)
    assert report["steps"] == 60
    assert report["tokens"] == 6000
    # The three legs multiply out to the headline ratio exactly.
    assert (report["availability_goodput"]
            * report["resource_goodput"]
            * report["program_goodput"]) == pytest.approx(
        report["goodput_ratio"])
    assert report["goodput_ratio"] == pytest.approx(0.6)


def test_backoff_attributed_not_unaccounted_partition_exact():
    """Satellite (PR 5): the retry supervisor's deliberate requeue
    delay is its own badput category. The backoff span sits INSIDE
    the retried task's queued span (requeue -> re-claim); the sweep
    charges those seconds to `backoff` exactly once — never to
    `queueing` twice, never leaking into `unaccounted` — and the
    partition stays exact."""
    events = [
        _ev(gp.TASK_RUNNING, 0.0, 10.0),          # attempt 1 (fails)
        _ev(gp.TASK_QUEUED, 10.0, 30.0),          # requeue -> claim
        _ev(gp.TASK_BACKOFF, 10.0, 18.0, retries=1,
            delay_seconds=8.0),                   # supervisor delay
        _ev(gp.TASK_RUNNING, 30.0, 90.0),         # attempt 2
        _ev(gp.PROGRAM_STEP_WINDOW, 30.0, 90.0, step_start=0,
            step_end=60),
    ]
    report = accounting.decompose(events)
    assert report["badput_seconds"]["backoff"] == pytest.approx(8.0)
    # Only the un-backed-off remainder of the wait is queueing.
    assert report["badput_seconds"]["queueing"] == pytest.approx(12.0)
    assert report["badput_seconds"]["unaccounted"] == pytest.approx(
        10.0)  # attempt 1's doomed run, nothing program-attributed
    assert report["productive_seconds"] == pytest.approx(60.0)
    total = (report["productive_seconds"]
             + sum(report["badput_seconds"].values())
             + sum(report["overlapped_seconds"].values()))
    assert total == pytest.approx(report["wall_seconds"])


def test_backoff_emitted_on_requeue_e2e(fakepod_env, tmp_path):
    """A requeued task's backoff wait is priced as TASK_BACKOFF —
    emitted by the CLAIM side once the wait elapsed (never
    future-dated: a report scraped mid-backoff must not extend wall
    past the present), and the pool report prices it."""
    store, substrate, pool = fakepod_env
    marker = tmp_path / "bo_marker"
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "jboff",
        "tasks": [{"id": "t0",
                   "command": f"test -f {marker} || "
                              f"{{ touch {marker}; exit 1; }}",
                   "max_task_retries": 2}],
    }]})
    jobs_mgr.add_jobs(store, pool, jobs)
    tasks = jobs_mgr.wait_for_tasks(store, pool.id, "jboff",
                                    timeout=30, poll_interval=0.2)
    assert tasks[0]["state"] == "completed"
    backoffs = [e for e in gp.query(store, pool.id)
                if e["kind"] == gp.TASK_BACKOFF]
    assert len(backoffs) == 1
    assert backoffs[0]["end"] > backoffs[0]["start"]
    # Never future-dated: the interval was fully elapsed at emit.
    assert backoffs[0]["end"] <= time.time()
    assert backoffs[0]["attrs"]["retries"] == 1
    report = accounting.pool_report(store, pool.id,
                                    include_jobs=False)
    assert report["badput_seconds"]["backoff"] > 0.0
    total = (report["productive_seconds"]
             + sum(report["badput_seconds"].values())
             + sum(report["overlapped_seconds"].values()))
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)


def test_cross_task_queue_wait_does_not_mask_productive_time():
    """T1 trains 0..100 while T2 waits in queue the whole time on a
    busy node: the node's time is productive; T2's wait is
    concurrency, not badput that erases T1's progress. Queue time
    only bites where nothing productive runs."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 100.0, task_id="t1",
            step_start=0, step_end=100),
        _ev(gp.TASK_QUEUED, 0.0, 110.0, task_id="t2"),
    ]
    report = accounting.decompose(events)
    assert report["productive_seconds"] == pytest.approx(100.0)
    assert report["badput_seconds"]["queueing"] == pytest.approx(10.0)


def test_overlap_resolution_checkpoint_beats_step_window():
    # Checkpoint saved INSIDE the step window: that slice is
    # checkpoint overhead, not productive time.
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 100.0),
        _ev(gp.PROGRAM_CHECKPOINT_SAVE, 40.0, 50.0),
    ]
    report = accounting.decompose(events)
    assert report["productive_seconds"] == pytest.approx(90.0)
    assert report["badput_seconds"]["checkpoint"] == pytest.approx(
        10.0)


def test_preemption_recovery_equals_replayed_step_window():
    """The acceptance-criteria scenario: train to step 100 with a
    checkpoint at 80, get preempted, restore to 80 and replay 80..100
    — the replayed window is ENTIRELY preemption-recovery badput."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 100.0, step_start=0,
            step_end=100),
        _ev(gp.PROGRAM_CHECKPOINT_SAVE, 100.0, 104.0, step=80),
        _ev(gp.PROGRAM_CHECKPOINT_RESTORE, 110.0, 112.0, step=80),
        # Replayed window: steps 80..100 were already done.
        _ev(gp.PROGRAM_STEP_WINDOW, 112.0, 132.0, step_start=80,
            step_end=100),
        # Fresh progress resumes.
        _ev(gp.PROGRAM_STEP_WINDOW, 132.0, 152.0, step_start=100,
            step_end=120),
    ]
    report = accounting.decompose(events)
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(20.0)
    assert report["productive_seconds"] == pytest.approx(100.0 + 20.0)
    # Partial replay: window crosses the high-water mark mid-way.
    events[3] = _ev(gp.PROGRAM_STEP_WINDOW, 112.0, 152.0,
                    step_start=80, step_end=120)
    del events[4]
    report = accounting.decompose(events)
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(20.0)


def test_step_counters_ignore_replayed_steps_in_totals():
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 10.0, step_start=0,
            step_end=10),
        _ev(gp.PROGRAM_STEP_WINDOW, 10.0, 20.0, step_start=0,
            step_end=10),
    ]
    report = accounting.decompose(events)
    # Whole second window is rework.
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(10.0)
    assert report["productive_seconds"] == pytest.approx(10.0)


def test_async_checkpoint_overlap_not_charged_as_badput():
    """Zero-stall checkpointing attribution: the blocking snapshot is
    checkpoint badput; the overlapped background persist under a live
    step window stays productive, and only its uncovered tail lands
    in the overlapped bucket — never in badput. Categories (incl.
    overlapped) still partition wall clock exactly."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 100.0),
        _ev(gp.PROGRAM_CHECKPOINT_SAVE, 40.0, 42.0, step=50,
            mode="snapshot"),
        # Persist overlaps the rest of the window, tail past it.
        _ev(gp.PROGRAM_CHECKPOINT_ASYNC, 42.0, 110.0, step=50),
    ]
    report = accounting.decompose(events)
    assert report["badput_seconds"]["checkpoint"] == pytest.approx(
        2.0)
    assert report["productive_seconds"] == pytest.approx(98.0)
    assert report["overlapped_seconds"][
        "checkpoint_async"] == pytest.approx(10.0)
    # Partition: productive + badput + overlapped == wall, exactly.
    total = (report["productive_seconds"]
             + sum(report["badput_seconds"].values())
             + sum(report["overlapped_seconds"].values()))
    assert total == pytest.approx(report["wall_seconds"])
    assert report["wall_seconds"] == pytest.approx(110.0)
    # The three legs still multiply out to the headline ratio.
    assert (report["availability_goodput"]
            * report["resource_goodput"]
            * report["program_goodput"]) == pytest.approx(
        report["goodput_ratio"])
    # Waterfall renders the overlapped row distinctly, outside the
    # badput set.
    table = accounting.waterfall_table(report)
    assert "~checkpoint_async" in table
    assert "not badput" in table
    lines = accounting.prometheus_lines(report, {"pool": "p1"})
    assert any('goodput_overlapped_seconds{pool="p1",'
               'category="checkpoint_async"} 10.0' in line
               for line in lines)


def test_retry_counted_and_empty_report_shape():
    report = accounting.decompose(
        [_ev(gp.TASK_RETRY, 5.0, 5.0, retries=1)])
    assert report["retries"] == 1
    empty = accounting.decompose([])
    assert empty["goodput_ratio"] == 0.0
    assert set(empty["badput_seconds"]) == set(
        accounting.BADPUT_CATEGORIES)


def test_concurrent_gang_windows_are_not_rework():
    """8 SPMD gang instances record the SAME step range at the same
    time — one unit of progress, not 7 replays. Only a window that
    starts after a prior window ENDED (post-restore replay) is
    rework."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 50.0, task_id="t1",
            node_id=f"n{i}", step_start=0, step_end=50)
        for i in range(8)
    ]
    report = accounting.decompose(events)
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(0.0)
    assert report["productive_seconds"] == pytest.approx(50.0)
    # A later (disjoint) replay of the same range IS rework.
    events.append(_ev(gp.PROGRAM_STEP_WINDOW, 60.0, 80.0,
                      task_id="t1", step_start=0, step_end=50))
    report = accounting.decompose(events)
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(20.0)


def test_rework_tracking_is_per_job():
    """Two unrelated jobs both training steps 0..50: neither is the
    other's replay — pool rollups must not misprice job B as rework."""
    events = [
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 50.0, job_id="jA",
            step_start=0, step_end=50),
        _ev(gp.PROGRAM_STEP_WINDOW, 60.0, 110.0, job_id="jB",
            step_start=0, step_end=50),
    ]
    report = accounting.decompose(events)
    assert report["badput_seconds"][
        "preemption_recovery"] == pytest.approx(0.0)
    assert report["productive_seconds"] == pytest.approx(100.0)


def test_prune_removes_only_old_events():
    import time as time_mod
    store = MemoryStateStore()
    now = time_mod.time()
    gp.emit(store, "p1", gp.NODE_IDLE, start=now - 10_000,
            end=now - 9_000)
    gp.emit(store, "p1", gp.NODE_IDLE, start=now - 100, end=now - 50)
    assert gp.prune(store, "p1", older_than_seconds=3_600) == 1
    remaining = gp.query(store, "p1")
    assert len(remaining) == 1
    assert remaining[0]["start"] == pytest.approx(now - 100)


def test_pool_report_trailing_window():
    import time as time_mod
    store = MemoryStateStore()
    now = time_mod.time()
    gp.emit(store, "p1", gp.PROGRAM_STEP_WINDOW, job_id="j1",
            start=now - 100, end=now - 40)
    gp.emit(store, "p1", gp.NODE_IDLE, node_id="n1",
            start=now - 50_000, end=now - 49_000)
    # Pool wall is NODE-seconds: 60s of (storeless-group) training +
    # 1000s of n1 idle, NOT the 50ks gap between them.
    full = accounting.pool_report(store, "p1")
    assert full["wall_seconds"] == pytest.approx(1060.0, abs=2.0)
    assert full["badput_seconds"]["idle"] == pytest.approx(1000.0)
    windowed = accounting.pool_report(store, "p1",
                                      window_seconds=3_600)
    assert windowed["wall_seconds"] == pytest.approx(60.0, abs=1.0)
    assert windowed["goodput_ratio"] == pytest.approx(1.0)


def test_pool_rollup_idle_nodes_not_shadowed_by_busy_node():
    """1 busy node + 7 concurrently idle nodes: a shared-timeline
    sweep would let the productive window shadow all the idle spans
    and report goodput ~1.0; per-node aggregation must surface the
    wasted capacity."""
    store = MemoryStateStore()
    gp.emit(store, "p1", gp.PROGRAM_STEP_WINDOW, job_id="j1",
            node_id="n0", start=0.0, end=100.0)
    for i in range(1, 8):
        gp.emit(store, "p1", gp.NODE_IDLE, node_id=f"n{i}",
                start=0.0, end=100.0)
    report = accounting.pool_report(store, "p1")
    assert report["wall_seconds"] == pytest.approx(800.0)
    assert report["badput_seconds"]["idle"] == pytest.approx(700.0)
    assert report["goodput_ratio"] == pytest.approx(1.0 / 8.0)
    assert report["nodes"] == 8


def test_pool_and_fleet_rollups():
    store = MemoryStateStore()
    store.upsert_entity("pools", "pools", "p1", {"state": "ready"})
    gp.emit(store, "p1", gp.PROGRAM_STEP_WINDOW, job_id="j1",
            start=0.0, end=50.0,
            attrs={"step_start": 0, "step_end": 50})
    gp.emit(store, "p1", gp.NODE_IDLE, node_id="n1", start=50.0,
            end=100.0)
    pool = accounting.pool_report(store, "p1")
    assert pool["wall_seconds"] == pytest.approx(100.0)
    assert pool["badput_seconds"]["idle"] == pytest.approx(50.0)
    assert "j1" in pool["jobs"]
    fleet = accounting.fleet_report(store)
    assert fleet["goodput_ratio"] == pytest.approx(0.5)
    assert "p1" in fleet["pools"]


def test_waterfall_and_prometheus_rendering():
    report = accounting.decompose([
        _ev(gp.PROGRAM_STEP_WINDOW, 0.0, 60.0),
        _ev(gp.PROGRAM_COMPILE, 60.0, 100.0),
    ])
    table = accounting.waterfall_table(report)
    assert "goodput_ratio = 0.600" in table
    for category in accounting.BADPUT_CATEGORIES:
        assert category in table
    lines = accounting.prometheus_lines(report, {"pool": "p1"})
    assert any(line.startswith('goodput_ratio{pool="p1"} 0.6')
               for line in lines)
    assert any('badput_seconds{pool="p1",category="compile"} 40.0'
               in line for line in lines)


# ------------------------- e2e on fakepod ------------------------------

@pytest.fixture()
def fakepod_env():
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    conf = {"pool_specification": {
        "id": "pool1", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16", "num_slices": 1},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    yield store, substrate, pool
    substrate.stop_all()


def test_e2e_job_goodput_report_sums_to_wall(fakepod_env):
    """The acceptance run: a localhost-class (fakepod) job whose
    payload records a program phase; the decomposition's categories
    must sum to wall clock within 1%."""
    store, substrate, pool = fakepod_env
    payload = (
        "python3 -c \"import json,os,time; t=time.time(); "
        "fh=open(os.environ['SHIPYARD_GOODPUT_FILE'],'a'); "
        "fh.write(json.dumps({'kind':'step_window','start':t,"
        "'end':t+0.08,'attrs':{'step_start':0,'step_end':8,"
        "'tokens':64}})+chr(10)); fh.close(); time.sleep(0.1)\"")
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "jgood", "tasks": [{"command": payload}]}]}))
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jgood",
                                    timeout=30)
    assert tasks[0]["state"] == "completed"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        kinds = {e["kind"] for e in gp.query(store, "pool1",
                                             job_id="jgood")}
        if {gp.TASK_QUEUED, gp.TASK_RUNNING,
                gp.PROGRAM_STEP_WINDOW} <= kinds:
            break
        time.sleep(0.1)
    assert {gp.TASK_QUEUED, gp.TASK_RUNNING,
            gp.PROGRAM_STEP_WINDOW} <= kinds
    report = accounting.job_report(store, "pool1", "jgood")
    assert report["wall_seconds"] > 0
    total = report["productive_seconds"] + sum(
        report["badput_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)
    assert report["productive_seconds"] > 0
    assert report["steps"] == 8
    assert report["tokens"] == 64
    # Satellite: job_stats aggregates sourced from the event log.
    stats = jobs_mgr.job_stats(store, "pool1", "jgood")
    assert stats["queue_seconds"] > 0
    assert stats["run_seconds"] > 0
    # Node-lifecycle events landed too (nodeprep marker, idle span).
    pool_kinds = {e["kind"] for e in gp.query(store, "pool1")}
    assert gp.NODE_IDLE in pool_kinds


def test_e2e_retry_emits_retry_events(fakepod_env):
    store, substrate, pool = fakepod_env
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "jretry",
            "tasks": [{"command": "exit 7",
                       "max_task_retries": 1}]}]}))
    jobs_mgr.wait_for_tasks(store, "pool1", "jretry", timeout=30)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        retries = [e for e in gp.query(store, "pool1",
                                       job_id="jretry")
                   if e["kind"] == gp.TASK_RETRY]
        if retries:
            break
        time.sleep(0.1)
    assert len(retries) == 1
    assert retries[0]["attrs"]["exit_code"] == 7
    report = accounting.job_report(store, "pool1", "jretry")
    assert report["retries"] == 1
    # The retried attempt's queue span starts at the REQUEUE, not the
    # original submit — the first attempt's runtime is not queueing.
    events = gp.query(store, "pool1", job_id="jretry")
    queued = [e for e in events if e["kind"] == gp.TASK_QUEUED]
    running = [e for e in events if e["kind"] == gp.TASK_RUNNING]
    assert len(queued) == 2 and len(running) == 2
    assert queued[1]["start"] >= running[0]["end"] - 0.5


# ------------------------------ CLI surface ----------------------------

def test_cli_goodput_and_jobs_wait(tmp_path):
    import yaml
    from click.testing import CliRunner

    from batch_shipyard_tpu.cli.main import cli
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"docker_images": []}},
        "pool": {"pool_specification": {
            "id": "gpool", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-8"},
            "max_wait_time_seconds": 30}},
        "jobs": {"job_specifications": [{
            "id": "gjob",
            "tasks": [{"command": "sleep 0.1 && echo done"}]}]},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    configdir = str(tmp_path)
    runner = CliRunner()
    result = runner.invoke(cli, ["--configdir", configdir, "pool",
                                 "add"], catch_exceptions=False)
    assert result.exit_code == 0
    result = runner.invoke(cli, ["--configdir", configdir, "jobs",
                                 "add"], catch_exceptions=False)
    assert result.exit_code == 0
    result = runner.invoke(
        cli, ["--configdir", configdir, "jobs", "wait", "--job-id",
              "gjob", "--timeout", "30", "--goodput-report"],
        catch_exceptions=False)
    assert result.exit_code == 0
    assert "goodput_ratio" in result.output
    result = runner.invoke(
        cli, ["--configdir", configdir, "--raw", "goodput", "job",
              "gjob"], catch_exceptions=False)
    assert result.exit_code == 0
    report = json.loads(result.output)
    assert report["job_id"] == "gjob"
    assert set(report["badput_seconds"]) == set(
        accounting.BADPUT_CATEGORIES)
    total = report["productive_seconds"] + sum(
        report["badput_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)
    for scope in (["goodput", "pool"], ["goodput", "fleet"]):
        result = runner.invoke(cli, ["--configdir", configdir]
                               + scope, catch_exceptions=False)
        assert result.exit_code == 0
        assert "goodput_ratio" in result.output


# ----------------------- atomic checkpoint commit ----------------------

def test_latest_step_skips_torn_checkpoints(tmp_path):
    """Regression for the torn-save pickup: an uncommitted
    step_NNNNNNNN dir (crash mid-save) must be invisible to
    latest_step/restore."""
    from batch_shipyard_tpu.workloads import checkpoint
    ckpt = tmp_path / "ckpt"
    committed = ckpt / "step_00000001"
    committed.mkdir(parents=True)
    (ckpt / ("step_00000001." + checkpoint.COMMIT_MARKER)).write_text(
        "ts")
    torn = ckpt / "step_00000002"  # no marker: simulated torn save
    torn.mkdir()
    assert checkpoint.latest_step(str(ckpt)) == 1
    # A stale staging dir is likewise ignored.
    (ckpt / ".tmp_step_00000003").mkdir()
    assert checkpoint.latest_step(str(ckpt)) == 1


def test_latest_step_accepts_legacy_pre_marker_dirs(tmp_path):
    """A checkpoint dir written entirely by pre-marker versions (no
    .COMMITTED files anywhere) keeps the old accept-all behavior —
    upgrading must not discard existing resume points."""
    from batch_shipyard_tpu.workloads import checkpoint
    ckpt = tmp_path / "legacy"
    (ckpt / "step_00000005").mkdir(parents=True)
    (ckpt / "step_00000009").mkdir()
    assert checkpoint.latest_step(str(ckpt)) == 9


def test_checkpoint_save_commits_atomically(tmp_path, monkeypatch):
    pytest.importorskip("orbax.checkpoint")
    import numpy as np

    from batch_shipyard_tpu.workloads import checkpoint
    goodput_file = tmp_path / "gp.jsonl"
    monkeypatch.setenv(gp.GOODPUT_FILE_ENV, str(goodput_file))
    ckpt = str(tmp_path / "ckpt")
    params = {"w": np.ones((2, 2), np.float32)}
    opt = {"m": np.zeros((2, 2), np.float32)}
    path = checkpoint.save(ckpt, 3, params, opt)
    assert checkpoint.is_committed(ckpt, 3)
    assert not os.path.exists(
        os.path.join(ckpt, ".tmp_step_00000003"))
    assert checkpoint.latest_step(ckpt) == 3
    restored = checkpoint.restore(ckpt, params, opt)
    assert restored is not None
    assert restored[2] == 3
    np.testing.assert_array_equal(restored[0]["w"], params["w"])
    assert os.path.basename(path) == "step_00000003"
    # Save + restore were recorded as checkpoint-overhead phases.
    kinds = [json.loads(line)["kind"] for line in
             goodput_file.read_text().splitlines()]
    assert gp.PROGRAM_CHECKPOINT_SAVE in kinds
    assert gp.PROGRAM_CHECKPOINT_RESTORE in kinds

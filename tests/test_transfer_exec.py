"""Execute the multinode transfer plan hermetically: a PATH-shimmed
scp/rsync copies locally, proving run_transfers drives the planned
command lines correctly (reference _multinode_transfer execution
path, data.py:712-739)."""

import os
import stat

import pytest

from batch_shipyard_tpu.data import movement


@pytest.fixture()
def fake_scp(tmp_path, monkeypatch):
    """An 'scp' that understands our planned argv shape and copies the
    source files into <dest_root>/<ip>/."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    dest_root = tmp_path / "received"
    dest_root.mkdir()
    script = bin_dir / "scp"
    script.write_text(f"""#!/usr/bin/env python3
import os, shutil, sys
args = sys.argv[1:]
files = []
it = iter(range(len(args)))
skip_next = False
for i, a in enumerate(args):
    if skip_next:
        skip_next = False
        continue
    if a in ('-o', '-P', '-i'):
        skip_next = True
        continue
    if a == '-p':
        continue
    files.append(a)
target = files.pop()  # user@ip:/path
ip = target.split('@')[1].split(':')[0]
out = os.path.join({str(dest_root)!r}, ip)
os.makedirs(out, exist_ok=True)
for f in files:
    shutil.copy(f, out)
""")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH",
                       f"{bin_dir}{os.pathsep}" + os.environ["PATH"])
    return dest_root


def test_run_transfers_executes_plan(tmp_path, fake_scp):
    src = tmp_path / "src"
    src.mkdir()
    files = []
    for idx, size in enumerate((500, 400, 100, 50)):
        path = src / f"f{idx}.bin"
        path.write_bytes(b"x" * size)
        files.append((str(path), size))
    nodes = [("n0", "10.0.0.1", 22), ("n1", "10.0.0.2", 22)]
    plan = movement.plan_multinode_transfer(files, nodes, "/data")
    rcs = movement.run_transfers(plan, max_parallel=2)
    assert rcs == [0, 0]
    received = {
        ip: sorted(os.listdir(fake_scp / ip))
        for ip in os.listdir(fake_scp)}
    # Every file delivered exactly once, across both nodes.
    all_received = [f for names in received.values() for f in names]
    assert sorted(all_received) == ["f0.bin", "f1.bin", "f2.bin",
                                    "f3.bin"]
    assert len(received) == 2


def test_ingress_data_global_files_spec(tmp_path):
    """The `data ingress` verb path with a storage destination."""
    from batch_shipyard_tpu.config import settings as settings_mod
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    src = tmp_path / "up"
    src.mkdir()
    (src / "model.ckpt").write_bytes(b"weights")
    global_conf = settings_mod.global_settings({
        "global_resources": {"files": [{
            "source": {"path": str(src)},
            "destination": {"storage": {"prefix": "ing/models"}},
        }]}})
    store = MemoryStateStore()
    count = movement.ingress_data(store, global_conf)
    assert count == 1
    assert store.get_object("ing/models/model.ckpt") == b"weights"

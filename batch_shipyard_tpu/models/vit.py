"""Vision Transformer (ViT) image classification: the framework's
encoder-attention model family (the reference's Caffe/MXNet/CNTK image
-classification recipes' modern analog — those recipes are thin
wrappers over framework containers, /root/reference/recipes/Caffe-GPU;
here the model IS part of the compute path).

TPU-first design decisions:
  - patch embedding as one reshape + Dense (a [B, N, P*P*3] x
    [P*P*3, D] matmul the MXU tiles directly — equivalent to the
    conv-stem formulation but stated as the matmul it is);
  - fixed 2D sin-cos position embeddings (no params, computed once at
    trace time — static shapes, nothing to shard);
  - non-causal attention through ops/attention.attention, so the same
    Pallas flash / blockwise dispatch as the LM applies;
  - bfloat16 activations with float32 LayerNorm statistics;
  - mean-pool head (no CLS token: a CLS token makes the patch count
    odd, which no TPU tiling likes). 128-aligned patch counts (e.g.
    image 256 / patch 16 -> 256) take the Pallas flash path; the
    classic 224/16 -> 196 does not tile the flash blocks, so those
    shapes run one monolithic online-softmax block instead — at ViT
    sequence lengths the score matrix is small enough that this is
    still MXU-bound.

Tensor/data-parallel sharding comes from parameter PartitionSpec rules
(parallel/sharding.py) exactly as for the LM — the module stays
sharding-agnostic.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from batch_shipyard_tpu.ops import attention as attn_ops


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    dropout: float = 0.0      # applied only when deterministic=False

    @property
    def num_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side


def sincos_2d_positions(side: int, dim: int) -> np.ndarray:
    """Fixed 2D sin-cos position table [side*side, dim] (half the
    channels encode the row coordinate, half the column)."""
    assert dim % 4 == 0, "sincos embedding needs dim % 4 == 0"
    quarter = dim // 4
    omega = 1.0 / (10000.0 ** (np.arange(quarter) / quarter))
    coords = np.arange(side, dtype=np.float64)
    args = np.outer(coords, omega)                     # [side, dim/4]
    table_1d = np.concatenate([np.sin(args), np.cos(args)], axis=1)
    rows = np.repeat(table_1d, side, axis=0)           # row-major grid
    cols = np.tile(table_1d, (side, 1))
    return np.concatenate([rows, cols], axis=1)        # [N, dim]


class LayerNorm(nn.Module):
    """LayerNorm with fp32 statistics regardless of activation dtype."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (dim,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (dim,),
                          jnp.float32)
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        normed = (x32 - mean) * jax.lax.rsqrt(var + 1e-6)
        return (normed * scale + bias).astype(self.dtype)


class EncoderBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        d_head = cfg.d_model // cfg.n_heads
        h = LayerNorm(dtype=cfg.dtype, name="attn_norm")(x)
        batch, seq = h.shape[0], h.shape[1]
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        q = dense(cfg.d_model, "q_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        k = dense(cfg.d_model, "k_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        v = dense(cfg.d_model, "v_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        # Non-128-aligned patch counts (224/16 -> 196) can't tile the
        # flash blocks; the dispatcher's gcd fallback would pick a
        # degenerate 4-wide block there, so force one full-width block
        # in that case (a single online-softmax step == plain
        # attention, fine at ViT sequence lengths).
        if attn_ops.flash_shapes_ok(seq, seq):
            out = attn_ops.attention(q, k, v, causal=False)
        else:
            out = attn_ops.attention(q, k, v, causal=False,
                                     impl="blockwise", block_size=seq)
        out = dense(cfg.d_model, "o_proj")(
            out.reshape(batch, seq, cfg.d_model))
        if cfg.dropout and not deterministic:
            out = nn.Dropout(cfg.dropout)(out,
                                          deterministic=deterministic)
        x = x + out
        h = LayerNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        h = dense(cfg.d_ff, "up_proj")(h)
        h = nn.gelu(h)
        h = dense(cfg.d_model, "down_proj")(h)
        if cfg.dropout and not deterministic:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        """images: [B, H, W, 3] -> logits [B, num_classes]."""
        cfg = self.config
        p = cfg.patch_size
        batch, height, width, chans = images.shape
        side = height // p
        # Patchify as pure reshapes: [B, s, p, s, p, C] -> [B, N, p*p*C]
        patches = images.reshape(batch, side, p, side, p, chans)
        patches = patches.transpose(0, 1, 3, 2, 4, 5).reshape(
            batch, side * side, p * p * chans)
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     name="patch_embed")(patches.astype(cfg.dtype))
        pos = jnp.asarray(sincos_2d_positions(side, cfg.d_model),
                          cfg.dtype)
        x = x + pos[None]
        for idx in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{idx}")(
                x, deterministic=deterministic)
        x = LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        pooled = jnp.mean(x.astype(jnp.float32), axis=1)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype,
                        name="head")(pooled)


def cross_entropy_loss(logits, labels):
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1],
                            dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logprobs, axis=-1))

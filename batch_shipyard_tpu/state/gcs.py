"""GCS-backed state store (cloud-scale implementation).

Maps the interface onto Google Cloud Storage primitives the same way
the reference maps onto Azure Storage (convoy/storage.py):

  - objects  -> GCS objects; ``if_generation_match`` is native.
  - leases   -> lease objects written with generation preconditions
               (create-only for acquire, matched overwrite for renew) —
               the GCS analog of Azure blob leases used by the cascade
               download gate (cascade.py:574-635) and the federation
               global lock (federation.py:962).
  - tables   -> one JSON object per entity under
               ``tables/<table>/<pk>/<rk>``; etag = str(generation).
  - queues   -> one JSON object per message under
               ``queues/<queue>/<id>``; claims via metadata patch with
               generation precondition (at-least-once semantics).

Requires ``google-cloud-storage`` and network access; import is lazy so
the rest of the framework is usable without either.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state import base
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, LeaseHandle, LeaseLostError,
    NotFoundError, ObjectMeta, PreconditionFailedError, QueueMessage)


class GCSStateStore(base.StateStore):
    def __init__(self, bucket: str, prefix: str = "shipyardtpu",
                 project: Optional[str] = None,
                 credentials_file: Optional[str] = None,
                 client=None, exceptions_module=None) -> None:
        """client/exceptions_module: injectable for tests (a faithful
        fake runs the whole contract suite against this class without
        a cloud account — tests/fake_gcs.py)."""
        if client is not None:
            self._client = client
            self._exceptions = exceptions_module
        else:
            try:
                from google.cloud import storage as gcs  # noqa: PLC0415
            except ImportError as exc:  # pragma: no cover
                raise RuntimeError(
                    "google-cloud-storage is required for the gcs "
                    "state backend; use backend: localfs or memory "
                    "otherwise") from exc
            if credentials_file:
                self._client = gcs.Client.from_service_account_json(
                    credentials_file, project=project)
            else:
                self._client = gcs.Client(project=project)
            self._exceptions = __import__(
                "google.api_core.exceptions", fromlist=["exceptions"])
        self._bucket = self._client.bucket(bucket)
        self._prefix = prefix.rstrip("/")

    # ------------------------------ helpers ----------------------------

    def _blob(self, key: str):
        return self._bucket.blob(f"{self._prefix}/{key}")

    def _wrap_precondition(self, exc: Exception, key: str) -> Exception:
        if isinstance(exc, self._exceptions.PreconditionFailed):
            return PreconditionFailedError(key)
        if isinstance(exc, self._exceptions.NotFound):
            return NotFoundError(key)
        return exc

    # ------------------------------ objects ----------------------------

    def put_object(self, key: str, data: bytes,
                   if_generation_match: Optional[int] = None) -> int:
        blob = self._blob(f"objects/{key}")
        try:
            blob.upload_from_string(
                data, if_generation_match=if_generation_match)
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)
        return int(blob.generation)

    def put_object_stream(self, key, chunks,
                          if_generation_match=None) -> int:
        """Native streaming via resumable upload from a file-like
        adapter over the chunk iterator — the object never
        materializes client-side."""
        import io

        class _IterReader(io.RawIOBase):
            def __init__(self, it):
                self._it = iter(it)
                self._buf = b""

            def readable(self):
                return True

            def readinto(self, b):
                while len(self._buf) < len(b):
                    try:
                        self._buf += next(self._it)
                    except StopIteration:
                        break
                n = min(len(b), len(self._buf))
                b[:n] = self._buf[:n]
                self._buf = self._buf[n:]
                return n

        blob = self._blob(f"objects/{key}")
        blob.chunk_size = self.STREAM_CHUNK_BYTES
        try:
            blob.upload_from_file(
                io.BufferedReader(_IterReader(chunks),
                                  self.STREAM_CHUNK_BYTES),
                if_generation_match=if_generation_match)
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)
        return int(blob.generation)

    def get_object_stream(self, key, chunk_size=None):
        chunk_size = chunk_size or self.STREAM_CHUNK_BYTES
        blob = self._blob(f"objects/{key}")
        try:
            blob.reload()
            size = blob.size or 0
            for start in range(0, size, chunk_size):
                end = min(start + chunk_size, size) - 1
                yield blob.download_as_bytes(start=start, end=end)
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)

    def generate_signed_url(self, key: str, method: str = "GET",
                            expires_seconds: float = 3600.0) -> str:
        """V4 signed URL for a single object (the `storage sas create`
        analog, reference shipyard.py:1327). Requires service-account
        credentials (ADC user credentials cannot sign); the
        google-auth error in that case is re-raised with the fix."""
        import datetime
        if method not in ("GET", "PUT", "DELETE", "HEAD"):
            raise ValueError(f"unsupported method {method!r}")
        blob = self._blob(f"objects/{key}")
        if method in ("GET", "HEAD") and not self.object_exists(key):
            raise NotFoundError(key)
        try:
            return blob.generate_signed_url(
                version="v4", method=method,
                expiration=datetime.timedelta(
                    seconds=expires_seconds))
        except Exception as exc:  # pragma: no cover - auth-specific
            if "private key" in str(exc).lower() or \
                    "credentials" in str(exc).lower():
                raise RuntimeError(
                    "signing requires service-account credentials "
                    "(credentials.storage.credentials_file or "
                    "service-account impersonation); user ADC "
                    f"cannot sign: {exc}") from exc
            raise

    def get_object(self, key: str) -> bytes:
        blob = self._blob(f"objects/{key}")
        try:
            return blob.download_as_bytes()
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)

    def get_object_meta(self, key: str) -> ObjectMeta:
        blob = self._blob(f"objects/{key}")
        try:
            blob.reload()
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)
        return ObjectMeta(key=key, size=blob.size or 0,
                          generation=int(blob.generation),
                          updated=blob.updated)

    def delete_object(self, key: str,
                      if_generation_match: Optional[int] = None) -> None:
        blob = self._blob(f"objects/{key}")
        try:
            blob.delete(if_generation_match=if_generation_match)
        except Exception as exc:  # pragma: no cover - network
            raise self._wrap_precondition(exc, key)

    def list_objects(self, prefix: str = "") -> list[str]:
        full = f"{self._prefix}/objects/{prefix}"
        strip = len(f"{self._prefix}/objects/")
        return sorted(
            b.name[strip:] for b in self._client.list_blobs(
                self._bucket, prefix=full))

    # ------------------------------ leases -----------------------------

    def acquire_lease(self, key: str, duration_seconds: float,
                      owner: str) -> Optional[LeaseHandle]:
        blob = self._blob(f"leases/{key}")
        now = time.time()
        token = uuid.uuid4().hex
        record = json.dumps({
            "owner": owner, "token": token,
            "expires_at": now + duration_seconds}).encode()
        try:
            blob.upload_from_string(record, if_generation_match=0)
            return LeaseHandle(key=key, owner=owner, token=token,
                               expires_at=now + duration_seconds)
        except self._exceptions.PreconditionFailed:
            pass
        # Held: steal only if expired, with a matched-generation swap.
        try:
            blob.reload()
            held = json.loads(blob.download_as_bytes())
        except self._exceptions.NotFound:
            return self.acquire_lease(key, duration_seconds, owner)
        if held["expires_at"] > now:
            return None
        try:
            blob.upload_from_string(
                record, if_generation_match=int(blob.generation))
            return LeaseHandle(key=key, owner=owner, token=token,
                               expires_at=now + duration_seconds)
        except self._exceptions.PreconditionFailed:
            return None

    def renew_lease(self, handle: LeaseHandle,
                    duration_seconds: float) -> LeaseHandle:
        blob = self._blob(f"leases/{handle.key}")
        now = time.time()
        try:
            blob.reload()
            held = json.loads(blob.download_as_bytes())
        except self._exceptions.NotFound:
            raise LeaseLostError(handle.key)
        if held["token"] != handle.token or held["expires_at"] <= now:
            raise LeaseLostError(handle.key)
        record = json.dumps({
            "owner": handle.owner, "token": handle.token,
            "expires_at": now + duration_seconds}).encode()
        try:
            blob.upload_from_string(
                record, if_generation_match=int(blob.generation))
        except self._exceptions.PreconditionFailed:
            raise LeaseLostError(handle.key)
        return LeaseHandle(key=handle.key, owner=handle.owner,
                           token=handle.token,
                           expires_at=now + duration_seconds)

    def release_lease(self, handle: LeaseHandle) -> None:
        blob = self._blob(f"leases/{handle.key}")
        try:
            # Capture the generation BEFORE validating the token, and
            # delete only if it still matches: if the lease expires and
            # is stolen at any point after the snapshot, the delete
            # fails with PreconditionFailed instead of destroying the
            # new owner's lease record.
            blob.reload()
            generation = int(blob.generation)
            held = json.loads(blob.download_as_bytes())
            if held["token"] != handle.token:
                raise LeaseLostError(handle.key)
            blob.delete(if_generation_match=generation)
        except self._exceptions.PreconditionFailed:
            raise LeaseLostError(handle.key)
        except self._exceptions.NotFound:
            raise LeaseLostError(handle.key)

    # ------------------------------ tables -----------------------------

    def _entity_blob(self, table: str, pk: str, rk: str):
        return self._blob(f"tables/{table}/{pk}/{rk}")

    def insert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        blob = self._entity_blob(table, partition_key, row_key)
        try:
            blob.upload_from_string(
                json.dumps(entity).encode(), if_generation_match=0)
        except self._exceptions.PreconditionFailed:
            raise EntityExistsError(f"{table}:{partition_key}:{row_key}")
        return str(blob.generation)

    def upsert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        blob = self._entity_blob(table, partition_key, row_key)
        blob.upload_from_string(json.dumps(entity).encode())
        return str(blob.generation)

    def merge_entity(self, table: str, partition_key: str, row_key: str,
                     entity: dict[str, Any],
                     if_match: Optional[str] = None) -> str:
        blob = self._entity_blob(table, partition_key, row_key)
        try:
            blob.reload()
            current = json.loads(blob.download_as_bytes())
        except self._exceptions.NotFound:
            raise NotFoundError(f"{table}:{partition_key}:{row_key}")
        etag = str(blob.generation)
        if if_match is not None and if_match != etag:
            raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
        current.update(entity)
        try:
            blob.upload_from_string(
                json.dumps(current).encode(),
                if_generation_match=int(etag))
        except self._exceptions.PreconditionFailed:
            raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
        return str(blob.generation)

    def get_entity(self, table: str, partition_key: str,
                   row_key: str) -> dict[str, Any]:
        blob = self._entity_blob(table, partition_key, row_key)
        try:
            blob.reload()
            out = json.loads(blob.download_as_bytes())
        except self._exceptions.NotFound:
            raise NotFoundError(f"{table}:{partition_key}:{row_key}")
        out["_etag"] = str(blob.generation)
        out["_pk"] = partition_key
        out["_rk"] = row_key
        return out

    def query_entities(self, table: str,
                       partition_key: Optional[str] = None,
                       row_key_prefix: str = "",
                       ) -> Iterator[dict[str, Any]]:
        prefix = f"{self._prefix}/tables/{table}/"
        if partition_key is not None:
            prefix += f"{partition_key}/{row_key_prefix}"
        for blob in self._client.list_blobs(self._bucket, prefix=prefix):
            parts = blob.name.split("/")
            pk, rk = parts[-2], parts[-1]
            if row_key_prefix and not rk.startswith(row_key_prefix):
                continue
            out = json.loads(blob.download_as_bytes())
            out["_etag"] = str(blob.generation)
            out["_pk"] = pk
            out["_rk"] = rk
            yield out

    def delete_entity(self, table: str, partition_key: str, row_key: str,
                      if_match: Optional[str] = None) -> None:
        blob = self._entity_blob(table, partition_key, row_key)
        try:
            blob.delete(if_generation_match=(
                int(if_match) if if_match is not None else None))
        except Exception as exc:
            exc2 = self._wrap_precondition(
                exc, f"{table}:{partition_key}:{row_key}")
            if isinstance(exc2, PreconditionFailedError):
                raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
            raise exc2

    # ------------------------------ queues -----------------------------
    # Message blob: queues/<queue>/<id> containing payload + visibility.
    # Claim = matched-generation rewrite bumping visible_at.

    def put_message(self, queue: str, payload: bytes,
                    delay_seconds: float = 0.0) -> str:
        message_id = f"{time.time():017.6f}-{uuid.uuid4().hex[:8]}"
        blob = self._blob(f"queues/{queue}/{message_id}")
        blob.upload_from_string(json.dumps({
            "payload": payload.hex(),
            "visible_at": time.time() + delay_seconds,
            "dequeue_count": 0,
        }).encode())
        return message_id

    def get_messages(self, queue: str, max_messages: int = 1,
                     visibility_timeout: float = 30.0,
                     ) -> list[QueueMessage]:
        now = time.time()
        out: list[QueueMessage] = []
        prefix = f"{self._prefix}/queues/{queue}/"
        for blob in self._client.list_blobs(self._bucket, prefix=prefix):
            if len(out) >= max_messages:
                break
            record = json.loads(blob.download_as_bytes())
            if record["visible_at"] > now:
                continue
            record["visible_at"] = now + visibility_timeout
            record["dequeue_count"] += 1
            receipt = uuid.uuid4().hex
            record["receipt"] = receipt
            try:
                blob.upload_from_string(
                    json.dumps(record).encode(),
                    if_generation_match=int(blob.generation))
            except self._exceptions.PreconditionFailed:
                continue  # another consumer won the claim race
            out.append(QueueMessage(
                queue=queue, message_id=blob.name.split("/")[-1],
                pop_receipt=receipt,
                payload=bytes.fromhex(record["payload"]),
                dequeue_count=record["dequeue_count"]))
        return out

    def _message_blob(self, message: QueueMessage):
        return self._blob(f"queues/{message.queue}/{message.message_id}")

    def delete_message(self, message: QueueMessage) -> None:
        blob = self._message_blob(message)
        try:
            record = json.loads(blob.download_as_bytes())
            if record.get("receipt") != message.pop_receipt:
                raise NotFoundError(message.message_id)
            blob.delete()
        except self._exceptions.NotFound:
            raise NotFoundError(message.message_id)

    def update_message(self, message: QueueMessage,
                       visibility_timeout: float) -> QueueMessage:
        blob = self._message_blob(message)
        try:
            blob.reload()
            record = json.loads(blob.download_as_bytes())
        except self._exceptions.NotFound:
            raise NotFoundError(message.message_id)
        if record.get("receipt") != message.pop_receipt:
            raise NotFoundError(message.message_id)
        record["visible_at"] = time.time() + visibility_timeout
        blob.upload_from_string(
            json.dumps(record).encode(),
            if_generation_match=int(blob.generation))
        return message

    def queue_length(self, queue: str) -> int:
        prefix = f"{self._prefix}/queues/{queue}/"
        return sum(1 for _ in self._client.list_blobs(
            self._bucket, prefix=prefix))

    def clear(self) -> None:  # pragma: no cover - destructive, cloud
        for blob in self._client.list_blobs(
                self._bucket, prefix=f"{self._prefix}/"):
            blob.delete()

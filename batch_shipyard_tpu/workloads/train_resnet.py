"""ResNet-50 training payload: the TensorFlow-Distributed recipe's
workload (ResNet-50/ImageNet shapes), TPU-native.

Runs single-chip or as a gang task across a pod slice (data parallel
over all global devices); synthetic data by default, or a directory of
.npy shards staged via input_data.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.train_resnet \
        --batch-per-device 128 --steps 50
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu import compilecache
from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.models import resnet as resnet_mod
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod
from batch_shipyard_tpu.workloads import checkpoint
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-per-device", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--data-dir", default=None,
                        help=".npz shard directory with images/labels "
                             "arrays (staged via input_data or a "
                             "gcsfuse mount); synthetic when omitted")
    parser.add_argument("--prefetch", type=int, default=2)
    checkpoint.add_checkpoint_args(parser)
    compilecache.add_compile_cache_args(parser)
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    batch_size = args.batch_per_device * n_dev
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = resnet_mod.ResNetConfig(num_classes=args.num_classes,
                                     dtype=jnp.bfloat16)
    # Warm-start compilation: persistent cache before the first jit;
    # --aot-precompile overlaps the step compile with the data
    # pipeline construction below.
    compilecache.enable_from_args(
        args, mesh_shape=dict(mesh.shape),
        model_digest=compilecache.config_digest(config))
    harness = train_mod.build_resnet_train(
        mesh, config, batch_size=batch_size,
        image_size=args.image_size)
    join_aot = (compilecache.aot.precompile_async(harness)
                if args.aot_precompile else None)
    from batch_shipyard_tpu.data import loader

    rng = np.random.RandomState(jax.process_index())
    # Each process loads only its slice of the global batch; the
    # prefetcher assembles the global array (multi-host aware).
    local_batch = batch_size // jax.process_count()
    if args.data_dir:
        dataset = loader.ShardedDataset(args.data_dir, local_batch)
        # Transfer compact uint8 and normalize ON DEVICE: host-side
        # float conversion made the pipeline the bottleneck (~4x
        # fewer bytes over PCIe and the VPU does the cast for free).
        normalize = jax.jit(
            lambda img: (img.astype(jnp.float32) / 127.5 - 1.0
                         ).astype(jnp.bfloat16),
            out_shardings=harness.batch_sharding)
        raw = loader.prefetch_to_device(iter(dataset),
                                        harness.batch_sharding,
                                        depth=args.prefetch)
        batches = ({"images": normalize(b["images"]),
                    "labels": b["labels"].astype(jnp.int32)}
                   for b in raw)
    else:
        synthetic = loader.place_global({
            "images": np.asarray(
                rng.randn(local_batch, args.image_size,
                          args.image_size, 3), np.float32
            ).astype(jnp.bfloat16),
            "labels": np.asarray(
                rng.randint(0, args.num_classes, (local_batch,)),
                np.int32),
        }, harness.batch_sharding)
        batches = loader.synthetic_batches(lambda step: synthetic)
    params, opt_state = harness.params, harness.opt_state
    ckpt = checkpoint.TrainCheckpointer.from_args(args)
    params, opt_state, start_step = ckpt.restore(params, opt_state)
    if start_step:
        distributed.log(ctx, f"resumed from step {start_step}")
    if join_aot is not None:
        join_aot()
    for _ in range(args.warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  next(batches))
        float(metrics["loss"])  # hard sync
    # On-demand profiling: `shipyard jobs profile` (trace/profiling).
    from batch_shipyard_tpu.trace.profiling import StepProfiler
    profiler = StepProfiler()
    start = time.perf_counter()
    for step_num in range(start_step, start_step + args.steps):
        profiler.tick(step_num)
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  next(batches))
        # Cooperative preemption: force-commit this boundary and exit
        # with the distinct preempted status (requeued at full
        # budget; the rerun resumes here).
        if ckpt.maybe_preempt(step_num + 1, params, opt_state):
            profiler.close()
            return preemption.EXIT_PREEMPTED
        ckpt.step_save(step_num + 1, params, opt_state)
    loss = float(metrics["loss"])
    profiler.close()
    elapsed = time.perf_counter() - start
    ckpt.finalize(start_step + args.steps, params, opt_state)
    images_per_sec = batch_size * args.steps / elapsed
    distributed.log(ctx, (
        f"resnet50: {images_per_sec:.1f} img/s total, "
        f"{images_per_sec / n_dev:.1f} img/s/chip, "
        f"loss={loss:.4f}, {elapsed / args.steps * 1000:.1f} ms/step"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Serving front end + load generator: HTTP ingress over the
continuous-batching engine, TTFT/TPOT measurement, Poisson load
report (VERDICT r3 order #4 — an Orca/vLLM-class engine is judged by
TTFT/TPOT under load, which needs an ingress path)."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import loadgen, serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.server import ServingFrontEnd, percentile

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(7),
                      jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture()
def front(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    fe = ServingFrontEnd(engine, port=0).start()
    yield fe
    fe.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        f"{url}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def test_generate_over_http_matches_engine_greedy(front, params):
    prompt = [5, 17, 31, 2]
    out = _post(front.url, {"prompt": prompt, "max_new_tokens": 6})
    assert len(out["tokens"]) == 6
    assert out["num_tokens"] == 6
    assert out["ttft_ms"] > 0 and out["tpot_ms"] >= 0
    assert out["latency_ms"] >= out["ttft_ms"]
    # Greedy equivalence with the lockstep decoder.
    run, _ = inf.make_decoder(CFG, params, max_decode_len=64)
    ref, _ = run(jnp.asarray([prompt], jnp.int32), 6,
                 jax.random.PRNGKey(0))
    assert out["tokens"] == list(
        np.asarray(ref[0, len(prompt):]).tolist())


def test_health_stats_and_errors(front):
    with urllib.request.urlopen(f"{front.url}/healthz",
                                timeout=30) as resp:
        assert json.loads(resp.read())["ok"] is True
    _post(front.url, {"prompt": [1, 2], "max_new_tokens": 3})
    with urllib.request.urlopen(f"{front.url}/v1/stats",
                                timeout=30) as resp:
        stats = json.loads(resp.read())
    assert stats["completed_requests"] >= 1
    assert stats["generated_tokens"] >= 3
    assert set(stats["ttft_ms"]) == {"50", "95", "99"} or set(
        stats["ttft_ms"]) == {50, 95, 99}
    # Bad request -> 400, server keeps serving.
    bad = urllib.request.Request(
        f"{front.url}/v1/generate",
        data=json.dumps({"prompt": "nope"}).encode(), method="POST")
    try:
        urllib.request.urlopen(bad, timeout=30)
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as exc:
        assert exc.code == 400
    out = _post(front.url, {"prompt": [3], "max_new_tokens": 2})
    assert len(out["tokens"]) == 2


def test_poisson_load_report(front):
    report = loadgen.run_load(
        front.url, num_requests=12, rate_hz=50.0,
        prompt_len=(2, 8), max_new_tokens=(2, 6), vocab_size=97,
        seed=3)
    assert report["completed"] == 12 and report["failed"] == 0
    assert report["generated_tokens"] >= 24
    assert report["tokens_per_second"] > 0
    for section in ("ttft_ms", "tpot_ms", "latency_ms"):
        assert set(report[section]) == {"p50", "p95", "p99"}
        assert report[section]["p99"] >= report[section]["p50"]
    hist = report["ttft_histogram"]
    assert sum(hist.values()) == 12
    # Reproducible arrivals + prompts under the same seed.
    again = loadgen.run_load(
        front.url, num_requests=3, rate_hz=100.0, prompt_len=(2, 4),
        max_new_tokens=(2, 3), vocab_size=97, seed=9)
    once_more = loadgen.run_load(
        front.url, num_requests=3, rate_hz=100.0, prompt_len=(2, 4),
        max_new_tokens=(2, 3), vocab_size=97, seed=9)
    assert again["generated_tokens"] == once_more["generated_tokens"]


def test_paged_overcommit_engine_behind_front(params):
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64,
        kv_page_size=8, kv_num_pages=12, overcommit=True)
    fe = ServingFrontEnd(engine, port=0).start()
    try:
        report = loadgen.run_load(
            fe.url, num_requests=6, rate_hz=100.0,
            prompt_len=(2, 6), max_new_tokens=(2, 8), vocab_size=97,
            seed=1)
        assert report["completed"] == 6 and report["failed"] == 0
    finally:
        fe.shutdown()


def test_percentile_nearest_rank():
    assert percentile([], 99) == 0.0
    vals = [float(v) for v in range(1, 101)]
    assert percentile(vals, 50) == 50.0
    assert percentile(vals, 99) == 99.0


def test_streaming_generate_ndjson(front, params):
    """stream: true returns one NDJSON line per token as it decodes,
    then the final result object; tokens match the blocking path."""
    import http.client
    host, port = front.address
    conn = http.client.HTTPConnection(host, port, timeout=120)
    body = json.dumps({"prompt": [5, 17, 31, 2],
                       "max_new_tokens": 5, "stream": True})
    conn.request("POST", "/v1/generate", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "application/x-ndjson"
    lines = [json.loads(ln) for ln in
             resp.read().decode().strip().split("\n")]
    conn.close()
    token_events = [e for e in lines if "token" in e]
    final = lines[-1]
    assert [e["index"] for e in token_events] == list(
        range(len(token_events)))
    assert final["tokens"] == [e["token"] for e in token_events]
    assert final["num_tokens"] == 5
    assert final["ttft_ms"] > 0
    # Same tokens as the blocking path (greedy, same prompt).
    blocking = _post(front.url, {"prompt": [5, 17, 31, 2],
                                 "max_new_tokens": 5})
    assert blocking["tokens"] == final["tokens"]
    # Bad streaming request -> clean 400 before any stream bytes.
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("POST", "/v1/generate",
                 body=json.dumps({"prompt": "bad", "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 400
    conn.close()


def test_streaming_engine_error_emitted_as_ndjson_line(front):
    """An engine-side rejection surfacing AFTER the chunked headers
    (e.g. prompt+generation exceeding max_decode_len) arrives as an
    {"error": ...} NDJSON line with a clean stream termination — not
    a second HTTP response corrupting the framing."""
    import http.client
    host, port = front.address
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/v1/generate",
                 body=json.dumps({"prompt": [1, 2, 3],
                                  "max_new_tokens": 100000,
                                  "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200  # headers already committed
    lines = [json.loads(ln) for ln in
             resp.read().decode().strip().split("\n")]
    conn.close()
    assert len(lines) == 1 and "error" in lines[0]
    assert "max_decode_len" in lines[0]["error"]
    # Server is still healthy afterwards.
    out = _post(front.url, {"prompt": [3], "max_new_tokens": 2})
    assert len(out["tokens"]) == 2
